"""Decode-side model: the KV-cache twin of ``models/transformer.py``.

``DecodeModel`` holds the decoder-LM weights in a canonical stacked layout
(per-layer arrays stacked on a leading L axis) plus the architecture facts
the weights alone cannot carry (head counts), and builds the two pure
functions the generate subsystem compiles:

- ``prefill_fn(params, tokens (1, T), length (1,))`` — full causal forward
  over a length-bucketed padded prompt, returning the next-token logits at
  position ``length - 1`` and the prompt's K/V laid out at slab capacity
  ``(L, 1, Hkv, C, Dh)``, ready to be slotted into a replica's KV slab.
- ``decode_fn(params, k_slab, v_slab, lengths (B,), tokens (B,))`` — ONE
  token for every slot at once: write each row's new k/v at position
  ``lengths[i]``, attend over its own prefix only
  (``ops.attention.cached_attention``), return (B, V) logits plus the
  updated slabs (donated — the steady-state step allocates nothing new).

The math mirrors ``models/transformer.py`` op for op (LayerNorm eps 1e-5,
no-bias q/k/v/o, RoPE on split heads at absolute positions, exact-match
gelu FFN, biased head) so a ``DecodeModel`` built from a Predictor's
loaded checkpoint produces the same distribution the fixed-shape serving
path scores — ``tests/test_serving_generate.py`` gates prefill logits
against ``Predictor.forward`` and decode logits against re-prefill.

Row independence is the correctness keystone: every per-position op is
row-local and ``cached_attention`` masks by the row's own length, so a
sequence's logits are bitwise identical regardless of which other
sequences share the batch — the continuous-batching invariant.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.attention import cached_attention, prefix_cached_attention, rope
from ...ops.matrix import quantized_matmul
from ..batcher import ServingError

#: MXNET_DECODE_KV_DTYPE -> slab element type (scales, int8 only, ride in
#: separate f32 slabs — see kv_scale_slab_shape)
KV_SLAB_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                  "int8": jnp.int8}


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Architecture facts not recoverable from weight shapes."""
    num_heads: int
    num_kv_heads: int = 0  # 0 = MHA (models/transformer.py convention)
    rope_base: float = 10000.0

    @property
    def hkv(self) -> int:
        return self.num_kv_heads or self.num_heads


def _ln(x, g, b, eps=1e-5):
    """ops.attention LayerNorm math (axis -1, eps 1e-5 — the op default
    models/transformer.py binds)."""
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _mm(params, x, name, l=None, act="int8"):
    """``x @ W.T`` with ``W = params[name]`` (``[l]`` when stacked).

    When ``mxnet_tpu.quant`` has rewritten this weight, a sibling
    ``<name>_scale`` entry exists and the matmul routes through
    ``ops.matrix.quantized_matmul`` (``act`` selects native-int8 vs
    dequant-on-load). With no scale entry this emits the exact
    pre-quantization expression — the quant-OFF jaxpr, and therefore the
    compiled program and its streams, are bitwise unchanged."""
    w = params[name] if l is None else params[name][l]
    sname = name + "_scale"
    if sname in params:
        s = params[sname] if l is None else params[sname][l]
        return quantized_matmul(x, w, s, act_dtype=act)
    return x @ w.T


def _quantize_kv(x):
    """Per-position symmetric int8 over the (Hkv, Dh) axes:
    ``x (..., Hkv, t, Dh) -> (q int8 same shape, scale (..., t) f32)``.
    Each cache position is written exactly once, so one scale per
    position never needs requantization — CoW forks copy scale rows
    alongside value blocks (ops.attention.dequantize_kv is the read-side
    inverse)."""
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))
    scale = jnp.maximum(amax, 1e-12).astype(jnp.float32) / 127.0
    q = jnp.clip(jnp.round(x / scale[..., None, :, None]), -127, 127)
    return q.astype(jnp.int8), scale


class DecodeModel:
    """Canonical stacked decoder-LM weights + derived dims.

    ``params`` (all jnp arrays): embed (V, D); stacked per-layer
    ln1_g/ln1_b/ln2_g/ln2_b (L, D), wq (L, D, D), wk/wv (L, Dkv, D),
    wo (L, D, D), w1 (L, F, D), b1 (L, F), w2 (L, D, F), b2 (L, D);
    lnf_g/lnf_b (D,), pred_w (V, D), pred_b (V,). FC weights keep the
    (out, in) orientation of ops.nn.FullyConnected.
    """

    def __init__(self, params: Dict[str, jnp.ndarray], spec: DecodeSpec):
        self.params = params
        self.spec = spec
        # matmul strategy when params carry quantized weights (set by
        # mxnet_tpu.quant.quantize_decode_model); inert without them
        self.quant_act = "int8"
        self.vocab, self.dm = params["embed"].shape
        self.layers = params["wq"].shape[0]
        self.dff = params["w1"].shape[1]
        if self.dm % spec.num_heads:
            raise ServingError("model_dim %d not divisible by num_heads %d"
                               % (self.dm, spec.num_heads))
        self.head_dim = self.dm // spec.num_heads
        want_dkv = self.head_dim * spec.hkv
        if params["wk"].shape[1] != want_dkv:
            raise ServingError(
                "k projection rows %d != num_kv_heads*head_dim %d — wrong "
                "num_heads/num_kv_heads for these weights?"
                % (params["wk"].shape[1], want_dkv))

    # --- construction ----------------------------------------------------
    @classmethod
    def from_arg_params(cls, arg_params: Dict, spec: DecodeSpec,
                        dtype="float32") -> "DecodeModel":
        """Build from ``models/transformer.py`` checkpoint naming (the
        dict a Predictor loads: embed_weight, layer%d_q_weight, ...).
        Accepts NDArray or numpy values."""
        def get(name):
            if name not in arg_params:
                raise ServingError(
                    "decode model: checkpoint lacks %r — is this a "
                    "models/transformer.py decoder LM?" % name)
            v = arg_params[name]
            v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
            return jnp.asarray(v.astype(dtype))

        n_layers = 0
        while ("layer%d_q_weight" % n_layers) in arg_params:
            n_layers += 1
        if n_layers == 0:
            raise ServingError("decode model: no layer0_q_weight in params")
        stacked: Dict[str, list] = {k: [] for k in (
            "ln1_g", "ln1_b", "wq", "wk", "wv", "wo", "ln2_g", "ln2_b",
            "w1", "b1", "w2", "b2")}
        for i in range(n_layers):
            p = "layer%d" % i
            stacked["ln1_g"].append(get(p + "_ln1_gamma"))
            stacked["ln1_b"].append(get(p + "_ln1_beta"))
            stacked["wq"].append(get(p + "_q_weight"))
            stacked["wk"].append(get(p + "_k_weight"))
            stacked["wv"].append(get(p + "_v_weight"))
            stacked["wo"].append(get(p + "_o_weight"))
            stacked["ln2_g"].append(get(p + "_ln2_gamma"))
            stacked["ln2_b"].append(get(p + "_ln2_beta"))
            stacked["w1"].append(get(p + "_ffn1_weight"))
            stacked["b1"].append(get(p + "_ffn1_bias"))
            stacked["w2"].append(get(p + "_ffn2_weight"))
            stacked["b2"].append(get(p + "_ffn2_bias"))
        params = {k: jnp.stack(v) for k, v in stacked.items()}
        params["embed"] = get("embed_weight")
        params["lnf_g"] = get("lnf_gamma")
        params["lnf_b"] = get("lnf_beta")
        params["pred_w"] = get("pred_weight")
        params["pred_b"] = get("pred_bias")
        return cls(params, spec)

    def kv_slab_shape(self, slots: int, capacity: int) -> tuple:
        """(L, slots, Hkv, C, Dh) — one of the two per-replica slabs."""
        return (self.layers, slots, self.spec.hkv, capacity, self.head_dim)

    def kv_scale_slab_shape(self, slots: int, capacity: int) -> tuple:
        """(L, slots, C) — per-position f32 scales for an int8 KV slab
        (one scale per cached position, shared across Hkv and Dh)."""
        return (self.layers, slots, capacity)

    def fingerprint_items(self):
        """(name, array) pairs in stable order, for the progcache model
        fingerprint (weights are program ARGS here, but the fingerprint
        still keys persisted metadata like ladders)."""
        return [(k, self.params[k]) for k in sorted(self.params)]

    # --- the two programs -------------------------------------------------
    def _project(self, h, l, b, t):
        """q/k/v projections of (b, t, D) -> split-head (b, {H|Hkv}, t, Dh),
        roped later (rope needs absolute positions)."""
        p, s = self.params, self.spec
        act = getattr(self, "quant_act", "int8")
        q = _mm(p, h, "wq", l, act)
        k = _mm(p, h, "wk", l, act)
        v = _mm(p, h, "wv", l, act)
        q = q.reshape(b, t, s.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, s.hkv, self.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, s.hkv, self.head_dim).transpose(0, 2, 1, 3)
        return q, k, v

    def _mlp(self, x, l):
        p = self.params
        act = getattr(self, "quant_act", "int8")
        h = _ln(x, p["ln2_g"][l], p["ln2_b"][l])
        h = jax.nn.gelu(_mm(p, h, "w1", l, act) + p["b1"][l])
        return x + (_mm(p, h, "w2", l, act) + p["b2"][l])

    def _head(self, x):
        p = self.params
        act = getattr(self, "quant_act", "int8")
        x = _ln(x, p["lnf_g"], p["lnf_b"])
        return _mm(p, x, "pred_w", None, act) + p["pred_b"]

    def build_prefill(self, bucket: int, capacity: int,
                      kv_dtype: str = "float32"):
        """Pure fn (params, tokens (1, T=bucket) i32, length (1,) i32) ->
        (logits (1, V) f32, k (L, 1, Hkv, C, Dh), v (...)). Padded
        positions >= length produce garbage kv that decode never reads
        (masked by length); the causal mask keeps them out of the
        returned last-real-position logits.

        ``kv_dtype`` re-types the RETURNED cache only (in-band prefill
        attention stays full precision — only stored state narrows):
        bf16 casts; int8 quantizes per position and appends (L, 1, C)
        k/v scale arrays to the outputs."""
        if bucket > capacity:
            raise ServingError("prefill bucket %d exceeds kv capacity %d"
                               % (bucket, capacity))
        spec = self.spec
        act = getattr(self, "quant_act", "int8")

        def prefill(params, tokens, length):
            self_p = DecodeModel.__new__(DecodeModel)
            self_p.params = params
            self_p.spec = spec
            self_p.quant_act = act
            self_p.vocab, self_p.dm = params["embed"].shape
            self_p.layers = params["wq"].shape[0]
            self_p.head_dim = self_p.dm // spec.num_heads
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            ks, vs = [], []
            for l in range(self_p.layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q, k, v = self_p._project(h, l, 1, bucket)
                q, k = rope(q, base=spec.rope_base), \
                    rope(k, base=spec.rope_base)
                # same fusion seam as the serving forward path: the flash
                # kernel owns the on-TPU/shape gate and falls back to the
                # grouped einsum / reference math off it
                from ...ops.pallas import flash_attention as _fa
                att = _fa.flash_attention(q, k, v, causal=True)
                att = att.transpose(0, 2, 1, 3).reshape(1, bucket, self_p.dm)
                x = x + _mm(params, att, "wo", l, act)
                x = self_p._mlp(x, l)
                ks.append(k)
                vs.append(v)
            logits = self_p._head(x)  # (1, T, V)
            last = jnp.take_along_axis(
                logits, (length - 1).astype(jnp.int32)[:, None, None], axis=1
            )[:, 0, :]
            pad = ((0, 0), (0, 0), (0, 0), (0, capacity - bucket), (0, 0))
            k_out = jnp.pad(jnp.stack(ks), pad)   # (L, 1, Hkv, C, Dh)
            v_out = jnp.pad(jnp.stack(vs), pad)
            if kv_dtype == "int8":
                kq, k_s = _quantize_kv(k_out)     # scales (L, 1, C)
                vq, v_s = _quantize_kv(v_out)
                return last, kq, vq, k_s, v_s
            if kv_dtype == "bfloat16":
                return (last, k_out.astype(jnp.bfloat16),
                        v_out.astype(jnp.bfloat16))
            return last, k_out, v_out

        return prefill

    def build_decode(self, slots: int, capacity: int,
                     kv_dtype: str = "float32"):
        """Pure fn (params, k_slab, v_slab, lengths (B,) i32, tokens (B,)
        i32) -> (logits (B, V), k_slab, v_slab). Slabs are meant to be
        donated by the compiler wrapper: steady state rewrites C-slices in
        place and allocates only the (B, V) logits. Inactive slots run
        with lengths pinned to 0 — wasted lanes, never wrong lanes.

        ``kv_dtype``: bf16 re-types the slabs (writes cast, reads flow
        through the f32-accumulating einsum). int8 inserts f32 scale
        slabs (L, B, C) into the signature — (params, k_slab, v_slab,
        ks_slab, vs_slab, lengths, tokens) -> (logits, k, v, ks, vs) —
        quantizing each new position BEFORE attention reads the slab, so
        a token's own step sees exactly the values every later step sees.
        f32 keeps the historical jaxpr bitwise (the astype below folds
        away)."""
        spec = self.spec
        act = getattr(self, "quant_act", "int8")

        def body(params, k_slab, v_slab, ks_slab, vs_slab, lengths,
                 tokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            lengths = lengths.astype(jnp.int32)
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            # rope positions: the new token sits at index `length`
            pos = lengths.reshape(slots, 1, 1)
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = _mm(params, h, "wq", l, act).reshape(
                    slots, spec.num_heads, 1, head_dim)
                k_t = _mm(params, h, "wk", l, act).reshape(
                    slots, spec.hkv, 1, head_dim)
                v_t = _mm(params, h, "wv", l, act).reshape(
                    slots, spec.hkv, 1, head_dim)
                q = rope(q, positions=pos, base=spec.rope_base)
                k_t = rope(k_t, positions=pos, base=spec.rope_base)

                def write(cache, new, p):
                    # cache (Hkv, C, Dh), new (Hkv, 1, Dh): row's k/v lands
                    # at its own position p = lengths[i]
                    return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

                if ks_slab is None:
                    k_l = jax.vmap(write)(k_slab[l],
                                          k_t.astype(k_slab.dtype), lengths)
                    v_l = jax.vmap(write)(v_slab[l],
                                          v_t.astype(v_slab.dtype), lengths)
                    k_slab = k_slab.at[l].set(k_l)
                    v_slab = v_slab.at[l].set(v_l)
                    att = cached_attention(q, k_l, v_l, lengths)
                else:
                    kq, k_s = _quantize_kv(k_t)   # scales (B, 1)
                    vq, v_s = _quantize_kv(v_t)
                    k_l = jax.vmap(write)(k_slab[l], kq, lengths)
                    v_l = jax.vmap(write)(v_slab[l], vq, lengths)

                    def write_s(row, new, p):
                        # row (C,), new (1,): scale lands beside its value
                        return jax.lax.dynamic_update_slice(row, new, (p,))

                    ks_l = jax.vmap(write_s)(ks_slab[l], k_s, lengths)
                    vs_l = jax.vmap(write_s)(vs_slab[l], v_s, lengths)
                    k_slab = k_slab.at[l].set(k_l)
                    v_slab = v_slab.at[l].set(v_l)
                    ks_slab = ks_slab.at[l].set(ks_l)
                    vs_slab = vs_slab.at[l].set(vs_l)
                    att = cached_attention(q, k_l, v_l, lengths,
                                           k_scale=ks_l, v_scale=vs_l)
                att = att.transpose(0, 2, 1, 3).reshape(slots, dm)
                x = x + _mm(params, att, "wo", l, act)
                h2 = _ln(x, params["ln2_g"][l], params["ln2_b"][l])
                h2 = jax.nn.gelu(_mm(params, h2, "w1", l, act)
                                 + params["b1"][l])
                x = x + (_mm(params, h2, "w2", l, act) + params["b2"][l])
            logits = _mm(params, _ln(x, params["lnf_g"], params["lnf_b"]),
                         "pred_w", None, act) + params["pred_b"]
            if ks_slab is None:
                return logits, k_slab, v_slab
            return logits, k_slab, v_slab, ks_slab, vs_slab

        if kv_dtype == "int8":
            def decode(params, k_slab, v_slab, ks_slab, vs_slab, lengths,
                       tokens):
                return body(params, k_slab, v_slab, ks_slab, vs_slab,
                            lengths, tokens)
        else:
            def decode(params, k_slab, v_slab, lengths, tokens):
                return body(params, k_slab, v_slab, None, None, lengths,
                            tokens)

        return decode

    def paged_slab_shape(self, num_blocks: int, block_tokens: int) -> tuple:
        """(L, num_blocks, Hkv, T, Dh) — one of the two paged slabs.
        ``num_blocks`` INCLUDES physical block 0, the reserved /dev/null
        block inactive lanes and padded positions write into."""
        return (self.layers, num_blocks, self.spec.hkv, block_tokens,
                self.head_dim)

    def paged_scale_slab_shape(self, num_blocks: int,
                               block_tokens: int) -> tuple:
        """(L, num_blocks, T) — per-position f32 scales for an int8 paged
        slab (block 0 included, same trash-block discipline)."""
        return (self.layers, num_blocks, block_tokens)

    def build_paged_prefill(self, bucket: int, block_tokens: int,
                            max_blocks: int, kv_dtype: str = "float32"):
        """Pure fn (params, k_slab, v_slab, table (MB,) i32, ctx_len ()
        i32, tokens (1, T=bucket) i32, n (1,) i32, fork_src () i32,
        fork_dst () i32) -> (logits (1, V), k_slab, v_slab).

        The paged admit path folds THREE things into one donated-slab
        program so the program set stays (ladder + one decode):

        1. **Copy-on-write fork**: physical block ``fork_src`` is copied
           into ``fork_dst`` first (both 0 — the trash block — when no
           fork), so a suffix that diverges inside a shared prefix block
           lands in a private copy while every other sharer keeps reading
           the original.
        2. **Chunked prefill over the cached prefix**: the first
           ``ctx_len`` positions are gathered from the slab via ``table``
           (shared prefix blocks materialize ONCE and are only read
           here); the ``n`` suffix tokens attend to that prefix plus
           causally to each other, roped at absolute positions
           ``ctx_len + j``.
        3. **Admit**: each suffix position's k/v is scattered to physical
           block ``table[(ctx_len + j) // T]`` offset ``(ctx_len + j) % T``
           (padded positions j >= n go to trash block 0).

        int8 ``kv_dtype`` adds scale slabs right after the value slabs
        (same donation discipline): (params, k_slab, v_slab, ks_slab,
        vs_slab, table, ...) -> (logits, k, v, ks, vs). The CoW fork
        copies scale blocks alongside value blocks, the prefix gather
        widens through the per-position scales, and the suffix scatter
        stores freshly quantized positions + their scales.
        """
        spec = self.spec
        act = getattr(self, "quant_act", "int8")
        T = int(block_tokens)
        mb = int(max_blocks)
        cap = T * mb

        def body(params, k_slab, v_slab, ks_slab, vs_slab, table, ctx_len,
                 tokens, n, fork_src, fork_dst):
            self_p = DecodeModel.__new__(DecodeModel)
            self_p.params = params
            self_p.spec = spec
            self_p.quant_act = act
            self_p.vocab, self_p.dm = params["embed"].shape
            self_p.layers = params["wq"].shape[0]
            self_p.head_dim = self_p.dm // spec.num_heads
            hkv = spec.hkv
            ctx_len = ctx_len.astype(jnp.int32)
            table = table.astype(jnp.int32)
            # (1) CoW fork: materialize the divergent block privately
            # before anything reads through the table (whose boundary
            # entry already names fork_dst).
            k_slab = k_slab.at[:, fork_dst].set(k_slab[:, fork_src])
            v_slab = v_slab.at[:, fork_dst].set(v_slab[:, fork_src])
            if ks_slab is not None:
                ks_slab = ks_slab.at[:, fork_dst].set(ks_slab[:, fork_src])
                vs_slab = vs_slab.at[:, fork_dst].set(vs_slab[:, fork_src])
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            j = jnp.arange(bucket, dtype=jnp.int32)
            pos = ctx_len + j                       # absolute positions
            # suffix k/v land at table[pos // T] : pos % T; padded lanes
            # (j >= n) land in trash block 0 (never read unmasked)
            phys = jnp.where(j < n[0],
                             table[jnp.clip(pos // T, 0, mb - 1)], 0)
            off = pos % T
            for l in range(self_p.layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q, k, v = self_p._project(h, l, 1, bucket)
                q = rope(q, positions=pos, base=spec.rope_base)
                k = rope(k, positions=pos, base=spec.rope_base)
                # (2) gather the cached prefix through the block table
                k_ctx = k_slab[l][table].transpose(1, 0, 2, 3) \
                    .reshape(1, hkv, cap, self_p.head_dim)
                v_ctx = v_slab[l][table].transpose(1, 0, 2, 3) \
                    .reshape(1, hkv, cap, self_p.head_dim)
                if ks_slab is None:
                    att = prefix_cached_attention(q, k_ctx, v_ctx, ctx_len,
                                                  k, v)
                else:
                    k_sctx = ks_slab[l][table].reshape(1, cap)
                    v_sctx = vs_slab[l][table].reshape(1, cap)
                    att = prefix_cached_attention(q, k_ctx, v_ctx, ctx_len,
                                                  k, v, k_scale=k_sctx,
                                                  v_scale=v_sctx)
                att = att.transpose(0, 2, 1, 3).reshape(1, bucket,
                                                        self_p.dm)
                x = x + _mm(params, att, "wo", l, act)
                x = self_p._mlp(x, l)
                # (3) admit: scatter this layer's suffix k/v into place
                if ks_slab is None:
                    k_slab = k_slab.at[l, phys, :, off, :].set(
                        k[0].transpose(1, 0, 2).astype(k_slab.dtype))
                    v_slab = v_slab.at[l, phys, :, off, :].set(
                        v[0].transpose(1, 0, 2).astype(v_slab.dtype))
                else:
                    kq, k_s = _quantize_kv(k)     # scales (1, bucket)
                    vq, v_s = _quantize_kv(v)
                    k_slab = k_slab.at[l, phys, :, off, :].set(
                        kq[0].transpose(1, 0, 2))
                    v_slab = v_slab.at[l, phys, :, off, :].set(
                        vq[0].transpose(1, 0, 2))
                    ks_slab = ks_slab.at[l, phys, off].set(k_s[0])
                    vs_slab = vs_slab.at[l, phys, off].set(v_s[0])
            logits = self_p._head(x)  # (1, T, V)
            last = jnp.take_along_axis(
                logits, (n - 1).astype(jnp.int32)[:, None, None], axis=1
            )[:, 0, :]
            if ks_slab is None:
                return last, k_slab, v_slab
            return last, k_slab, v_slab, ks_slab, vs_slab

        if kv_dtype == "int8":
            def prefill(params, k_slab, v_slab, ks_slab, vs_slab, table,
                        ctx_len, tokens, n, fork_src, fork_dst):
                return body(params, k_slab, v_slab, ks_slab, vs_slab,
                            table, ctx_len, tokens, n, fork_src, fork_dst)
        else:
            def prefill(params, k_slab, v_slab, table, ctx_len, tokens, n,
                        fork_src, fork_dst):
                return body(params, k_slab, v_slab, None, None, table,
                            ctx_len, tokens, n, fork_src, fork_dst)

        return prefill

    def build_paged_decode(self, slots: int, block_tokens: int,
                           max_blocks: int, kv_dtype: str = "float32"):
        """Pure fn (params, k_slab, v_slab, tables (B, MB) i32, lengths
        (B,) i32, tokens (B,) i32) -> (logits (B, V), k_slab, v_slab).

        The paged twin of ``build_decode``: each row's new k/v is
        scattered to physical block ``tables[i, lengths[i] // T]`` offset
        ``lengths[i] % T`` (the scheduler guarantees that block is
        PRIVATE to row i — copy-on-write resolves sharing before any
        write is scheduled), then attention gathers the row's dense
        (Hkv, C, Dh) view through its table and masks by length exactly
        like the unpaged step. Inactive lanes carry an all-zero table, so
        their writes land in trash block 0 — wasted lanes, never wrong
        lanes, same fixed-shape discipline as the unpaged program.

        int8 ``kv_dtype`` adds scale slabs (L, NB, T) after the value
        slabs, written at the same (phys_w, off_w) site and gathered
        per row as (B, C) for the widening read — see ``build_decode``
        for the read-your-own-write ordering argument.
        """
        spec = self.spec
        act = getattr(self, "quant_act", "int8")
        T = int(block_tokens)
        mb = int(max_blocks)
        cap = T * mb

        def body(params, k_slab, v_slab, ks_slab, vs_slab, tables,
                 lengths, tokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            hkv = spec.hkv
            lengths = lengths.astype(jnp.int32)
            tables = tables.astype(jnp.int32)
            x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
            pos = lengths.reshape(slots, 1, 1)
            # write site per row: its own (always-private) block
            phys_w = jnp.take_along_axis(
                tables, jnp.clip(lengths // T, 0, mb - 1)[:, None],
                axis=1)[:, 0]
            off_w = lengths % T
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = _mm(params, h, "wq", l, act).reshape(
                    slots, spec.num_heads, 1, head_dim)
                k_t = _mm(params, h, "wk", l, act).reshape(
                    slots, hkv, 1, head_dim)
                v_t = _mm(params, h, "wv", l, act).reshape(
                    slots, hkv, 1, head_dim)
                q = rope(q, positions=pos, base=spec.rope_base)
                k_t = rope(k_t, positions=pos, base=spec.rope_base)
                if ks_slab is not None:
                    kq, k_s = _quantize_kv(k_t)   # scales (B, 1)
                    vq, v_s = _quantize_kv(v_t)
                    k_slab = k_slab.at[l, phys_w, :, off_w, :].set(
                        kq[:, :, 0, :])
                    v_slab = v_slab.at[l, phys_w, :, off_w, :].set(
                        vq[:, :, 0, :])
                    ks_slab = ks_slab.at[l, phys_w, off_w].set(k_s[:, 0])
                    vs_slab = vs_slab.at[l, phys_w, off_w].set(v_s[:, 0])
                else:
                    k_slab = k_slab.at[l, phys_w, :, off_w, :].set(
                        k_t[:, :, 0, :].astype(k_slab.dtype))
                    v_slab = v_slab.at[l, phys_w, :, off_w, :].set(
                        v_t[:, :, 0, :].astype(v_slab.dtype))
                # gather each row's dense view (write first, so the new
                # token's k/v is visible to its own attention)
                k_l = k_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                v_l = v_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                if ks_slab is not None:
                    ks_l = ks_slab[l][tables].reshape(slots, cap)
                    vs_l = vs_slab[l][tables].reshape(slots, cap)
                    att = cached_attention(q, k_l, v_l, lengths,
                                           k_scale=ks_l, v_scale=vs_l)
                else:
                    att = cached_attention(q, k_l, v_l, lengths)
                att = att.transpose(0, 2, 1, 3).reshape(slots, dm)
                x = x + _mm(params, att, "wo", l, act)
                h2 = _ln(x, params["ln2_g"][l], params["ln2_b"][l])
                h2 = jax.nn.gelu(_mm(params, h2, "w1", l, act)
                                 + params["b1"][l])
                x = x + (_mm(params, h2, "w2", l, act) + params["b2"][l])
            logits = _mm(params, _ln(x, params["lnf_g"], params["lnf_b"]),
                         "pred_w", None, act) + params["pred_b"]
            if ks_slab is None:
                return logits, k_slab, v_slab
            return logits, k_slab, v_slab, ks_slab, vs_slab

        if kv_dtype == "int8":
            def decode(params, k_slab, v_slab, ks_slab, vs_slab, tables,
                       lengths, tokens):
                return body(params, k_slab, v_slab, ks_slab, vs_slab,
                            tables, lengths, tokens)
        else:
            def decode(params, k_slab, v_slab, tables, lengths, tokens):
                return body(params, k_slab, v_slab, None, None, tables,
                            lengths, tokens)

        return decode

    def build_verify(self, slots: int, capacity: int, window: int,
                     kv_dtype: str = "float32"):
        """Pure fn (params, k_slab, v_slab, lengths (B,) i32, wtokens
        (B, W) i32) -> (logits (B, W, V), k_slab, v_slab) — the
        speculative-decode verify program (serving/generate/spec.py).

        A batched W-position forward per row: ``wtokens[i] = [last_token,
        d_1 .. d_k]`` (W = k + 1 draft window) sits at absolute positions
        ``lengths[i] + j``, attends to the row's cached prefix
        (``prefix_cached_attention`` with per-row ctx_len — positions
        >= lengths[i] in the slab are masked, so the draft pass's scratch
        writes are invisible) plus causally to earlier window positions,
        and every window position's k/v is scattered back into the slab —
        OVERWRITING the draft model's scratch rows with target-exact
        values, which is what makes rewind a pure length edit. Writes at
        positions >= capacity are dropped (out-of-bounds scatter). Shapes
        are independent of how many draft tokens end up accepted:
        ``logits[i, j]`` is the target's next-token distribution after
        sequence position ``lengths[i] + j``, and the host picks the
        longest matching prefix / runs rejection sampling over it.

        ``kv_dtype``: bf16 writes cast; int8 quantizes each window
        position (same per-position scales as ``build_decode``) and feeds
        the attention the quantized-then-dequantized values, so a window
        position's own logits see exactly the cache bytes every later
        step reads — the read-your-own-write discipline that keeps
        accept-path streams bitwise equal to vanilla decode."""
        spec = self.spec
        act = getattr(self, "quant_act", "int8")
        W = int(window)

        def body(params, k_slab, v_slab, ks_slab, vs_slab, lengths,
                 wtokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            hkv = spec.hkv
            lengths = lengths.astype(jnp.int32)
            x = jnp.take(params["embed"], wtokens.astype(jnp.int32), axis=0)
            pos = lengths[:, None] + jnp.arange(W, dtype=jnp.int32)  # (B, W)
            rows = jnp.arange(slots, dtype=jnp.int32)[:, None]       # (B, 1)
            rpos = pos[:, None, :]            # (B, 1, W): rope over heads
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = _mm(params, h, "wq", l, act).reshape(
                    slots, W, spec.num_heads, head_dim).transpose(0, 2, 1, 3)
                k_t = _mm(params, h, "wk", l, act).reshape(
                    slots, W, hkv, head_dim).transpose(0, 2, 1, 3)
                v_t = _mm(params, h, "wv", l, act).reshape(
                    slots, W, hkv, head_dim).transpose(0, 2, 1, 3)
                q = rope(q, positions=rpos, base=spec.rope_base)
                k_t = rope(k_t, positions=rpos, base=spec.rope_base)
                if ks_slab is not None:
                    kq, k_s = _quantize_kv(k_t)   # scales (B, W)
                    vq, v_s = _quantize_kv(v_t)
                    k_slab = k_slab.at[l, rows, :, pos, :].set(
                        kq.transpose(0, 2, 1, 3), mode="drop")
                    v_slab = v_slab.at[l, rows, :, pos, :].set(
                        vq.transpose(0, 2, 1, 3), mode="drop")
                    ks_slab = ks_slab.at[l, rows, pos].set(k_s, mode="drop")
                    vs_slab = vs_slab.at[l, rows, pos].set(v_s, mode="drop")
                    # window keys as later reads will see them: quantized
                    # then widened (dequantize_kv's math, in-register)
                    k_win = kq.astype(jnp.float32) * k_s[:, None, :, None]
                    v_win = vq.astype(jnp.float32) * v_s[:, None, :, None]
                    att = prefix_cached_attention(
                        q, k_slab[l], v_slab[l], lengths[:, None], k_win,
                        v_win, k_scale=ks_slab[l], v_scale=vs_slab[l])
                else:
                    k_w = k_t.astype(k_slab.dtype)
                    v_w = v_t.astype(v_slab.dtype)
                    k_slab = k_slab.at[l, rows, :, pos, :].set(
                        k_w.transpose(0, 2, 1, 3), mode="drop")
                    v_slab = v_slab.at[l, rows, :, pos, :].set(
                        v_w.transpose(0, 2, 1, 3), mode="drop")
                    att = prefix_cached_attention(
                        q, k_slab[l], v_slab[l], lengths[:, None], k_w, v_w)
                att = att.transpose(0, 2, 1, 3).reshape(slots, W, dm)
                x = x + _mm(params, att, "wo", l, act)
                x = self._mlp_p(params, x, l, act)
            logits = _mm(params, _ln(x, params["lnf_g"], params["lnf_b"]),
                         "pred_w", None, act) + params["pred_b"]
            if ks_slab is None:
                return logits, k_slab, v_slab
            return logits, k_slab, v_slab, ks_slab, vs_slab

        if kv_dtype == "int8":
            def verify(params, k_slab, v_slab, ks_slab, vs_slab, lengths,
                       wtokens):
                return body(params, k_slab, v_slab, ks_slab, vs_slab,
                            lengths, wtokens)
        else:
            def verify(params, k_slab, v_slab, lengths, wtokens):
                return body(params, k_slab, v_slab, None, None, lengths,
                            wtokens)

        return verify

    def build_paged_verify(self, slots: int, block_tokens: int,
                           max_blocks: int, window: int,
                           kv_dtype: str = "float32"):
        """Paged twin of ``build_verify``: (params, k_slab, v_slab,
        tables (B, MB) i32, lengths (B,) i32, wtokens (B, W) i32) ->
        (logits (B, W, V), k_slab, v_slab).

        Window position ``lengths[i] + j`` scatters to physical block
        ``tables[i, (lengths[i]+j) // T]`` offset ``% T`` — positions at
        or past capacity, and positions beyond the row's block
        reservation (table entry 0), land in trash block 0, never read
        unmasked. The admission reservation already covers every position
        a stream can ever COMMIT (``min(prompt + max_new, capacity)``),
        so accepted tokens always land in reserved private blocks and the
        speculative tail needs no allocation — rewind stays a host-side
        length edit (``PagedKVCacheManager.truncate``)."""
        spec = self.spec
        act = getattr(self, "quant_act", "int8")
        T = int(block_tokens)
        mb = int(max_blocks)
        cap = T * mb
        W = int(window)

        def body(params, k_slab, v_slab, ks_slab, vs_slab, tables,
                 lengths, wtokens):
            dm = params["embed"].shape[1]
            n_layers = params["wq"].shape[0]
            head_dim = dm // spec.num_heads
            hkv = spec.hkv
            lengths = lengths.astype(jnp.int32)
            tables = tables.astype(jnp.int32)
            x = jnp.take(params["embed"], wtokens.astype(jnp.int32), axis=0)
            pos = lengths[:, None] + jnp.arange(W, dtype=jnp.int32)  # (B, W)
            rpos = pos[:, None, :]
            # write sites: clip is NOT enough here — clamping pos >= cap
            # into the last table entry would wrap onto a REAL block, so
            # out-of-range positions are routed to trash explicitly
            phys = jnp.where(
                pos < cap,
                jnp.take_along_axis(tables,
                                    jnp.clip(pos // T, 0, mb - 1), axis=1),
                0)
            off = pos % T
            for l in range(n_layers):
                h = _ln(x, params["ln1_g"][l], params["ln1_b"][l])
                q = _mm(params, h, "wq", l, act).reshape(
                    slots, W, spec.num_heads, head_dim).transpose(0, 2, 1, 3)
                k_t = _mm(params, h, "wk", l, act).reshape(
                    slots, W, hkv, head_dim).transpose(0, 2, 1, 3)
                v_t = _mm(params, h, "wv", l, act).reshape(
                    slots, W, hkv, head_dim).transpose(0, 2, 1, 3)
                q = rope(q, positions=rpos, base=spec.rope_base)
                k_t = rope(k_t, positions=rpos, base=spec.rope_base)
                if ks_slab is not None:
                    kq, k_s = _quantize_kv(k_t)   # scales (B, W)
                    vq, v_s = _quantize_kv(v_t)
                    k_slab = k_slab.at[l, phys, :, off, :].set(
                        kq.transpose(0, 2, 1, 3))
                    v_slab = v_slab.at[l, phys, :, off, :].set(
                        vq.transpose(0, 2, 1, 3))
                    ks_slab = ks_slab.at[l, phys, off].set(k_s)
                    vs_slab = vs_slab.at[l, phys, off].set(v_s)
                    k_win = kq.astype(jnp.float32) * k_s[:, None, :, None]
                    v_win = vq.astype(jnp.float32) * v_s[:, None, :, None]
                else:
                    k_win = k_t.astype(k_slab.dtype)
                    v_win = v_t.astype(v_slab.dtype)
                    k_slab = k_slab.at[l, phys, :, off, :].set(
                        k_win.transpose(0, 2, 1, 3))
                    v_slab = v_slab.at[l, phys, :, off, :].set(
                        v_win.transpose(0, 2, 1, 3))
                # gather each row's dense ctx view through its table
                # (write-first like build_paged_decode; the window span is
                # masked by the per-row ctx_len anyway)
                k_l = k_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                v_l = v_slab[l][tables].transpose(0, 2, 1, 3, 4) \
                    .reshape(slots, hkv, cap, head_dim)
                if ks_slab is not None:
                    ks_l = ks_slab[l][tables].reshape(slots, cap)
                    vs_l = vs_slab[l][tables].reshape(slots, cap)
                    att = prefix_cached_attention(
                        q, k_l, v_l, lengths[:, None], k_win, v_win,
                        k_scale=ks_l, v_scale=vs_l)
                else:
                    att = prefix_cached_attention(
                        q, k_l, v_l, lengths[:, None], k_win, v_win)
                att = att.transpose(0, 2, 1, 3).reshape(slots, W, dm)
                x = x + _mm(params, att, "wo", l, act)
                x = self._mlp_p(params, x, l, act)
            logits = _mm(params, _ln(x, params["lnf_g"], params["lnf_b"]),
                         "pred_w", None, act) + params["pred_b"]
            if ks_slab is None:
                return logits, k_slab, v_slab
            return logits, k_slab, v_slab, ks_slab, vs_slab

        if kv_dtype == "int8":
            def verify(params, k_slab, v_slab, ks_slab, vs_slab, tables,
                       lengths, wtokens):
                return body(params, k_slab, v_slab, ks_slab, vs_slab,
                            tables, lengths, wtokens)
        else:
            def verify(params, k_slab, v_slab, tables, lengths, wtokens):
                return body(params, k_slab, v_slab, None, None, tables,
                            lengths, wtokens)

        return verify

    @staticmethod
    def _mlp_p(params, x, l, act):
        """``_mlp`` against explicit params (builders close over the
        traced params argument, not ``self.params``)."""
        h = _ln(x, params["ln2_g"][l], params["ln2_b"][l])
        h = jax.nn.gelu(_mm(params, h, "w1", l, act) + params["b1"][l])
        return x + (_mm(params, h, "w2", l, act) + params["b2"][l])

    def build_admit(self, slots: int, capacity: int,
                    kv_dtype: str = "float32"):
        """Pure fn (k_slab, v_slab, k_new (L,1,Hkv,C,Dh), v_new, slot i32)
        -> updated slabs (donated): slot a freshly prefilled sequence's kv
        into its allocated row. int8 ``kv_dtype`` extends both sides with
        the (L, 1, C) scale rows prefill returned."""
        if kv_dtype == "int8":
            def admit(k_slab, v_slab, ks_slab, vs_slab, k_new, v_new,
                      ks_new, vs_new, slot):
                slot = slot.astype(jnp.int32)
                z = jnp.int32(0)
                return (jax.lax.dynamic_update_slice(k_slab, k_new,
                                                     (z, slot, z, z, z)),
                        jax.lax.dynamic_update_slice(v_slab, v_new,
                                                     (z, slot, z, z, z)),
                        jax.lax.dynamic_update_slice(ks_slab, ks_new,
                                                     (z, slot, z)),
                        jax.lax.dynamic_update_slice(vs_slab, vs_new,
                                                     (z, slot, z)))

            return admit

        def admit(k_slab, v_slab, k_new, v_new, slot):
            slot = slot.astype(jnp.int32)
            z = jnp.int32(0)
            return (jax.lax.dynamic_update_slice(k_slab, k_new,
                                                 (z, slot, z, z, z)),
                    jax.lax.dynamic_update_slice(v_slab, v_new,
                                                 (z, slot, z, z, z)))

        return admit


def infer_spec_dims(arg_params: Dict) -> Dict[str, int]:
    """Dims recoverable from a models/transformer.py checkpoint (vocab,
    model_dim, ffn_dim, layers) — head counts must come from DecodeSpec."""
    embed = arg_params["embed_weight"]
    shape = embed.shape
    n_layers = 0
    while ("layer%d_q_weight" % n_layers) in arg_params:
        n_layers += 1
    ffn1 = arg_params["layer0_ffn1_weight"]
    return {"vocab": int(shape[0]), "model_dim": int(shape[1]),
            "layers": n_layers, "ffn_dim": int(ffn1.shape[0])}
