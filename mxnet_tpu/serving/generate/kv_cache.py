"""Slot-allocated KV-cache slabs, one pair per replica.

``KVCacheManager`` owns the decode state the engine serializes: the two
``(L, slots, Hkv, C, Dh)`` slabs live behind ONE engine variable per
replica, and every program that touches them (admit, step) is pushed
with ``mutable_vars=[var]`` — the engine's dependency ordering then
serializes step N+1 after step N (and after any admits between them)
with no lock of our own around device work.

The *host-side* bookkeeping (which slot belongs to which sequence, each
row's current length) is protected by ``_lock`` — a LEAF lock in the
declared hierarchy (rank 100): nothing is ever acquired under it, and it
is never held across an engine push or device call.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import List, Optional

import numpy as np

from ... import engine as _engine
from ..batcher import ServingError
from .programs import DecodePrograms


@dataclasses.dataclass
class AdmitPlan:
    """Everything the scheduler needs to turn one queued prompt into one
    admission op — the shared currency of ``KVCacheManager.try_admit``
    and ``PagedKVCacheManager.try_admit``. Unpaged plans carry only
    (slot, suffix = whole prompt); paged plans add the cached-prefix
    length, the block table, and the copy-on-write fork pair."""
    slot: int
    suffix: List[int]          # tokens the prefill program must run
    ctx_len: int = 0           # cached-prefix tokens reused (0 = cold)
    table: Optional[np.ndarray] = None   # (max_blocks,) i32, paged only
    fork_src: int = 0          # shared block to CoW-copy (0 = no fork)
    fork_dst: int = 0          # private target of the copy (0 = no fork)

    @property
    def forked(self) -> bool:
        return self.fork_dst != 0


class KVCacheManager:
    """Slot allocator + slab holder for one replica's decode state."""

    def __init__(self, programs: DecodePrograms, replica: int = 0):
        self.programs = programs
        self.replica = replica
        self.slots = programs.slots
        self.capacity = programs.capacity
        self.var = _engine.new_variable()
        _engine.track_inflight(self.var)
        self.k_slab, self.v_slab = programs.fresh_slabs()
        # int8 KV: per-position f32 scale slabs travel with the value
        # slabs through every program (same engine var, same donation)
        scales = programs.fresh_scale_slabs()
        self.k_scale, self.v_scale = scales if scales else (None, None)
        self._lock = threading.Lock()
        # host mirrors: lengths[i] = tokens materialized in row i's kv
        # (prompt + generated so far); owner[i] = opaque sequence tag
        self._lengths = np.zeros(self.slots, np.int32)
        self._owner: List[Optional[object]] = [None] * self.slots
        # explicit free list so alloc/free are O(1) — a linear scan under
        # the lock is invisible at 4 slots but not at paged-scale counts
        self._free_slots: deque = deque(range(self.slots))

    # --- slot bookkeeping (host-only, leaf lock) -------------------------
    def alloc(self, owner, prompt_len: int) -> Optional[int]:
        """Claim a free slot for ``owner``; None if the batch is full."""
        if prompt_len > self.capacity:
            raise ServingError(
                "prompt length %d exceeds kv capacity %d"
                % (prompt_len, self.capacity), code="too_large")
        with self._lock:
            if not self._free_slots:
                return None
            slot = self._free_slots.popleft()
            self._owner[slot] = owner
            self._lengths[slot] = prompt_len
            return slot

    def try_admit(self, owner, prompt, max_new: int) -> Optional[AdmitPlan]:
        """Admission in plan form (the scheduler's single entry point for
        both cache kinds). Unpaged: a slot is the whole reservation and
        the suffix is the whole prompt; ``max_new`` is unused because
        every slot already owns a full-capacity lane."""
        slot = self.alloc(owner, len(prompt))
        if slot is None:
            return None
        return AdmitPlan(slot=slot, suffix=[int(t) for t in prompt])

    def free(self, slot: int):
        with self._lock:
            if self._owner[slot] is None:
                return                      # idempotent double-free guard
            self._owner[slot] = None
            self._lengths[slot] = 0
            self._free_slots.append(slot)

    def advance(self, slot: int) -> int:
        """Record one decoded token in ``slot``; returns the new length."""
        with self._lock:
            self._lengths[slot] += 1
            return int(self._lengths[slot])

    def length(self, slot: int) -> int:
        with self._lock:
            return int(self._lengths[slot])

    def truncate(self, slot: int, new_len: int):
        """Rewind ``slot``'s bookkeeping to ``new_len`` tokens — the
        speculative-decode reject path. Slab rows past ``new_len`` keep
        stale K/V, but every read is length-masked and the next verify
        rewrites the window before any of it is unmasked, so the rewind
        is this one host-side assignment."""
        with self._lock:
            self._lengths[slot] = int(new_len)

    def owner(self, slot: int):
        with self._lock:
            return self._owner[slot]

    def active_slots(self) -> List[int]:
        with self._lock:
            return [i for i in range(self.slots)
                    if self._owner[i] is not None]

    def occupancy_pct(self) -> float:
        with self._lock:
            used = sum(1 for o in self._owner if o is not None)
        return 100.0 * used / self.slots

    def step_arrays(self):
        """(lengths, mask) snapshots for the next decode step: inactive or
        capacity-full rows get length 0 (their lane runs but the result is
        discarded — fixed shape beats re-compiling per occupancy)."""
        with self._lock:
            lengths = self._lengths.copy()
            mask = np.array([o is not None for o in self._owner], bool)
        return lengths, mask

    # --- slab plumbing (scheduler thread only) ---------------------------
    def swap_slabs(self, k_slab, v_slab, k_scale=None, v_scale=None):
        """Adopt the donated-output slabs a step/admit program returned
        (int8 KV programs also return the scale slabs)."""
        self.k_slab, self.v_slab = k_slab, v_slab
        if k_scale is not None:
            self.k_scale, self.v_scale = k_scale, v_scale

    def reset(self):
        """Fresh slabs + empty bookkeeping (server restart)."""
        with self._lock:
            self._lengths[:] = 0
            self._owner = [None] * self.slots
            self._free_slots = deque(range(self.slots))
        self.k_slab, self.v_slab = self.programs.fresh_slabs()
        scales = self.programs.fresh_scale_slabs()
        self.k_scale, self.v_scale = scales if scales else (None, None)

    def kv_bytes(self) -> int:
        return self.programs.kv_bytes()
