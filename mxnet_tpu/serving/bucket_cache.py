"""Bucketed executor cache: one compiled XLA program per batch bucket.

The BucketingModule discipline (module/bucketing_module.py: N symbols,
ONE shared parameter set) applied to inference serving: requests are
padded up to the nearest configured batch bucket, so steady-state traffic
touches only len(buckets) compiled programs and never recompiles. Bucket
executors are built lazily via ``Predictor.reshape`` — weights are shared
by reference, only the XLA program is per-bucket — and the base
predictor's own program is enrolled as its bucket, so a server over
buckets (1, 4, 8) costs exactly three compilations, ever.

This is the economics the TPU-compilation literature dictates (Fisher &
Besard; "Operator Fusion in XLA"): XLA programs are shape-specialized, so
serving must quantize shapes, not chase them.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from .. import progcache as _progcache
from ..analysis import compile_witness as _witness
from .batcher import ServingError


class BucketCache:
    """Lazy per-bucket executor cache over a base ``predict.Predictor``.

    ``buckets`` are batch sizes along ``axis`` 0 of every input. The base
    predictor must be bound at per-example shapes consistent with the
    bucket shapes; if its batch size IS one of the buckets (the server
    binds it at the smallest), its already-compiled program is reused —
    enrollment is not a miss.
    """

    def __init__(self, base, buckets: Sequence[int], device=None):
        if not buckets:
            raise ServingError("at least one bucket batch size required")
        self.buckets: List[int] = sorted(set(int(b) for b in buckets))
        if self.buckets[0] < 1:
            raise ServingError("bucket batch sizes must be >= 1")
        self._base = base
        self._device = device
        self._lock = threading.Lock()
        self._execs: Dict[int, object] = {}
        self.hits = 0
        self.misses = 0
        # a miss builds the bucket's program one of two ways — a fresh XLA
        # compile, or a disk load from the persistent progcache. The split
        # is what lets the dryrun (and an operator) tell a warm restart
        # from a compile storm.
        self.compiles = 0
        self.disk_hits = 0
        # per-instance compile-witness scope: the inner Predictor compile
        # (or progcache disk load) a reshape triggers is tagged with it,
        # so stats() can report the witness ledger's split when armed
        self._witness_scope = _witness.new_scope()
        # LRU bookkeeping for ladder swaps: logical tick per get(), so
        # set_ladder can retire the programs traffic stopped touching
        self._tick = 0
        self._last_used: Dict[int, int] = {}
        # enroll the base program if it is bound at a bucket batch size
        base_batch = {s[0] for s in base._input_shapes.values()}
        if len(base_batch) == 1 and next(iter(base_batch)) in self.buckets:
            self._execs[next(iter(base_batch))] = base
        # per-example shapes (batch axis stripped) for reshape
        self._example_shapes = {n: tuple(s[1:])
                                for n, s in base._input_shapes.items()}

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket >= rows (the padding target)."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise ServingError(
            "request of %d rows exceeds the largest bucket (%d); raise "
            "MXNET_SERVING_BUCKETS or split the request"
            % (rows, self.buckets[-1]), "error")

    def get(self, bucket: int):
        """The compiled executor for ``bucket`` (compiling on first use)."""
        with self._lock:
            self._tick += 1
            exe = self._execs.get(bucket)
            if exe is not None:
                self.hits += 1
                self._last_used[bucket] = self._tick
                return exe
            if bucket not in self.buckets:
                raise ServingError("%d is not a configured bucket (%s)"
                                   % (bucket, self.buckets))
            self.misses += 1
            shapes = {n: (bucket,) + s
                      for n, s in self._example_shapes.items()}
            with _witness.surface(self._witness_scope):
                exe = self._base.reshape(shapes, device=self._device)
            self._count_build(exe)
            self._execs[bucket] = exe
            self._last_used[bucket] = self._tick
            return exe

    def _count_build(self, exe):
        """A miss was just filled: either a fresh XLA compile or a disk
        load from the persistent progcache (Predictor.progcache_source).
        Callers hold ``_lock``."""
        if getattr(exe, "progcache_source", "compile") == "disk":
            self.disk_hits += 1
        else:
            self.compiles += 1

    def acquire(self, rows: int):
        """``(bucket, executor)`` for ``rows`` against the CURRENT ladder,
        atomically wrt ``set_ladder`` — the pair a dispatch needs under one
        lock hold, so a concurrent swap can never retire the chosen bucket
        between choosing and fetching it (requests must survive retunes)."""
        with self._lock:
            self._tick += 1
            bucket = None
            for b in self.buckets:
                if b >= rows:
                    bucket = b
                    break
            if bucket is None:
                raise ServingError(
                    "request of %d rows exceeds the largest bucket (%d); "
                    "raise MXNET_SERVING_BUCKETS or split the request"
                    % (rows, self.buckets[-1]), "error")
            exe = self._execs.get(bucket)
            if exe is not None:
                self.hits += 1
                self._last_used[bucket] = self._tick
                return bucket, exe
            self.misses += 1
            shapes = {n: (bucket,) + s
                      for n, s in self._example_shapes.items()}
            with _witness.surface(self._witness_scope):
                exe = self._base.reshape(shapes, device=self._device)
            self._count_build(exe)
            self._execs[bucket] = exe
            self._last_used[bucket] = self._tick
            return bucket, exe

    def prepare(self, bucket: int):
        """Compile-ahead for ``bucket`` without blocking the hot path: the
        reshape (and its XLA compile) runs OUTSIDE ``_lock`` — reshape is
        pure wrt the base, it builds a fresh executor sharing params by
        reference — and the program is enrolled under the lock afterwards,
        first writer wins. The bucket need not be in the current ladder:
        this is the warmup half of a ladder swap (``set_ladder``)."""
        bucket = int(bucket)
        if bucket < 1:
            raise ServingError("bucket batch sizes must be >= 1")
        with self._lock:
            exe = self._execs.get(bucket)
            if exe is not None:
                return exe
        shapes = {n: (bucket,) + s
                  for n, s in self._example_shapes.items()}
        with _witness.surface(self._witness_scope):
            exe = self._base.reshape(shapes, device=self._device)
        with self._lock:
            cur = self._execs.get(bucket)
            if cur is not None:
                return cur  # lost the race; the duplicate program is dropped
            self._count_build(exe)
            self._execs[bucket] = exe
            self._last_used[bucket] = self._tick
            return exe

    def set_ladder(self, new_buckets: Sequence[int],
                   budget: Optional[int] = None) -> List[int]:
        """Swap the bucket ladder atomically; returns the retired buckets.

        The new ladder must keep ``max_batch`` (so every request the
        server ever admitted still finds a bucket — a swap can never
        strand an in-flight request). Programs for retired buckets are
        forgotten LRU-first; a dispatch already holding its executor
        reference is unaffected — retirement only drops the cache entry,
        the program dies when its last reference does."""
        nb = sorted(set(int(b) for b in new_buckets))
        if not nb:
            raise ServingError("at least one bucket batch size required")
        if nb[0] < 1:
            raise ServingError("bucket batch sizes must be >= 1")
        with self._lock:
            if nb[-1] != self.buckets[-1]:
                raise ServingError(
                    "ladder swap must preserve max_batch %d (got %s)"
                    % (self.buckets[-1], nb))
            self.buckets = nb
            keep = set(nb)
            retired = sorted((b for b in self._execs if b not in keep),
                             key=lambda b: self._last_used.get(b, -1))
            if budget is not None and len(keep & set(self._execs)) > budget:
                raise ServingError(
                    "ladder %s exceeds the program budget %d" % (nb, budget))
            for b in retired:
                del self._execs[b]
                self._last_used.pop(b, None)
        # version the persistent cache with the new ladder (outside _lock:
        # progcache does its own locking and file I/O): the tuned ladder is
        # saved so a restarted server adopts it immediately, and the kept
        # buckets' entries get their LRU clocks bumped so the byte budget
        # ages out the retired programs first.
        self._progcache_sync(nb)
        return retired

    # --- persistent-cache integration ------------------------------------
    def _model_fp(self) -> Optional[str]:
        """The base predictor's model fingerprint (None when the
        persistent cache is disabled or the base can't be hashed)."""
        if not _progcache.enabled():
            return None
        fp = getattr(self._base, "_progcache_model_fp", None)
        if fp is None:
            try:
                fp = _progcache.model_fingerprint(
                    self._base._symbol, self._base._arg_params,
                    self._base._aux_params)
                self._base._progcache_model_fp = fp
            except Exception:
                return None
        return fp

    def _bucket_key(self, fp: str, bucket: int) -> str:
        shapes = {n: (bucket,) + s for n, s in self._example_shapes.items()}
        device = (self._device if self._device is not None
                  else self._base._device)
        return _progcache.predictor_key(
            fp, list(shapes), shapes, self._base._dtype, device)

    def _progcache_sync(self, buckets: List[int]):
        fp = self._model_fp()
        if fp is None:
            return
        _progcache.save_ladder(fp, buckets)
        for b in buckets:
            _progcache.touch(self._bucket_key(fp, b))

    def restore_ladder(self, budget: Optional[int] = None) -> bool:
        """Adopt the ladder a previous process persisted for this model
        (``progcache.save_ladder``), so a warm restart starts at the TUNED
        ladder — and disk-loads exactly those programs — instead of
        rediscovering it from live traffic. Returns True when a persisted
        ladder was adopted. The persisted ladder must agree on max_batch
        (the swap invariant) and fit ``budget``; otherwise it is ignored."""
        fp = self._model_fp()
        if fp is None:
            return False
        ladder = _progcache.load_ladder(fp)
        if not ladder or ladder == self.buckets:
            return False
        if ladder[-1] != self.max_batch:
            return False
        if budget is not None and len(ladder) > budget:
            return False
        self.set_ladder(ladder, budget)
        return True

    def warm(self):
        """Precompile every bucket (trade startup time for tail latency)."""
        for b in self.buckets:
            with self._lock:
                have = b in self._execs
            if not have:
                self.get(b)

    def stats(self) -> Dict[str, object]:
        """``compiles`` counts FRESH XLA compiles only; ``disk_hits`` are
        misses filled from the persistent progcache; ``cache_hits`` is the
        in-memory hit count (alias of the historical ``hits`` key, kept
        for compatibility). With the compile witness armed the
        compile/disk split comes from the witness ledger (this cache's
        scope), so the split and the process-wide counters can never
        disagree."""
        with self._lock:
            out = {"hits": self.hits, "cache_hits": self.hits,
                   "misses": self.misses, "compiles": self.compiles,
                   "disk_hits": self.disk_hits,
                   "buckets": list(self.buckets),
                   "compiled": sorted(self._execs)}
        if _witness.enabled():
            sc = _witness.scope_counts(self._witness_scope)
            out["compiles"] = sc["compiles"]
            out["disk_hits"] = sc["disk_hits"]
        return out
