"""Bucketed executor cache: one compiled XLA program per batch bucket.

The BucketingModule discipline (module/bucketing_module.py: N symbols,
ONE shared parameter set) applied to inference serving: requests are
padded up to the nearest configured batch bucket, so steady-state traffic
touches only len(buckets) compiled programs and never recompiles. Bucket
executors are built lazily via ``Predictor.reshape`` — weights are shared
by reference, only the XLA program is per-bucket — and the base
predictor's own program is enrolled as its bucket, so a server over
buckets (1, 4, 8) costs exactly three compilations, ever.

This is the economics the TPU-compilation literature dictates (Fisher &
Besard; "Operator Fusion in XLA"): XLA programs are shape-specialized, so
serving must quantize shapes, not chase them.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from .batcher import ServingError


class BucketCache:
    """Lazy per-bucket executor cache over a base ``predict.Predictor``.

    ``buckets`` are batch sizes along ``axis`` 0 of every input. The base
    predictor must be bound at per-example shapes consistent with the
    bucket shapes; if its batch size IS one of the buckets (the server
    binds it at the smallest), its already-compiled program is reused —
    enrollment is not a miss.
    """

    def __init__(self, base, buckets: Sequence[int], device=None):
        if not buckets:
            raise ServingError("at least one bucket batch size required")
        self.buckets: List[int] = sorted(set(int(b) for b in buckets))
        if self.buckets[0] < 1:
            raise ServingError("bucket batch sizes must be >= 1")
        self._base = base
        self._device = device
        self._lock = threading.Lock()
        self._execs: Dict[int, object] = {}
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        # enroll the base program if it is bound at a bucket batch size
        base_batch = {s[0] for s in base._input_shapes.values()}
        if len(base_batch) == 1 and next(iter(base_batch)) in self.buckets:
            self._execs[next(iter(base_batch))] = base
        # per-example shapes (batch axis stripped) for reshape
        self._example_shapes = {n: tuple(s[1:])
                                for n, s in base._input_shapes.items()}

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket >= rows (the padding target)."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise ServingError(
            "request of %d rows exceeds the largest bucket (%d); raise "
            "MXNET_SERVING_BUCKETS or split the request"
            % (rows, self.buckets[-1]), "error")

    def get(self, bucket: int):
        """The compiled executor for ``bucket`` (compiling on first use)."""
        with self._lock:
            exe = self._execs.get(bucket)
            if exe is not None:
                self.hits += 1
                return exe
            if bucket not in self.buckets:
                raise ServingError("%d is not a configured bucket (%s)"
                                   % (bucket, self.buckets))
            self.misses += 1
            shapes = {n: (bucket,) + s
                      for n, s in self._example_shapes.items()}
            exe = self._base.reshape(shapes, device=self._device)
            self.compiles += 1
            self._execs[bucket] = exe
            return exe

    def warm(self):
        """Precompile every bucket (trade startup time for tail latency)."""
        for b in self.buckets:
            with self._lock:
                have = b in self._execs
            if not have:
                self.get(b)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "compiles": self.compiles, "buckets": list(self.buckets),
                    "compiled": sorted(self._execs)}
