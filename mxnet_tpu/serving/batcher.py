"""Request queue + micro-batch former for the serving subsystem.

The TPU inversion of the reference's per-process CPU predictor
(c_predict_api): throughput comes from coalescing concurrent requests
into a few fixed shapes and keeping the device fed. ``BatchFormer`` is
the coalescing stage — a bounded FIFO with per-request deadlines and a
max-batch-size / max-queue-delay window (the standard dynamic-batching
contract: dispatch as soon as ``max_batch`` rows are queued OR the oldest
request has waited ``max_delay_ms``, whichever first).

Failure is structured: every way a request can fail carries a
``ServingError`` with a machine-readable ``code`` —

- ``queue_full``         backpressure: the bounded queue rejected the submit
- ``too_large``          the request's rows exceed ``max_batch``; it could
                         never be dispatched, so submit rejects it
- ``deadline_exceeded``  the request expired before dispatch
- ``shutdown``           the server stopped while the request was queued
- ``shutting_down``      the server is draining (``stop(drain=True)``):
                         new submits are refused, and requests still
                         queued when the drain deadline passes fail too
- ``dispatch_error``     the compiled executor raised; the batch's requests
                         all carry the cause
- ``wait_timeout``       ``Request.get(timeout)`` gave up waiting
- ``cancelled``          the caller cancelled an in-flight generate stream
                         (``TokenStream.cancel()``); its slot is freed at
                         the next scheduler step
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError
from .. import telemetry
from ..telemetry import flight as _flight


class ServingError(MXNetError):
    """Structured serving failure; ``code`` is machine-readable (see module
    docstring for the vocabulary)."""

    def __init__(self, msg: str, code: str = "error"):
        super().__init__(msg)
        self.code = code


#: priority/QoS classes, in admission order: interactive requests are
#: always dispatched before batch-class requests queued at the same time
#: (FIFO within a class — a class never reorders internally)
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1
_N_PRIORITIES = 2


class Request:
    """One in-flight request: a dict of name -> np.ndarray with a leading
    batch axis (usually 1 row; small batches ride whole — the former never
    splits a request across micro-batches). ``priority`` is the QoS class
    (``PRIORITY_INTERACTIVE``/``PRIORITY_BATCH``); ``request_id`` is an
    opaque caller correlation id echoed by the HTTP front-end;
    ``trace`` is the request's propagated ``telemetry.TraceContext``
    (or None) — the object carry that survives the HTTP-thread →
    former-thread → engine-worker hops."""

    __slots__ = ("inputs", "rows", "deadline", "submitted", "latency_ms",
                 "priority", "request_id", "trace", "_event", "_outputs",
                 "_error")

    def __init__(self, inputs: Dict[str, np.ndarray], rows: int,
                 deadline: Optional[float], priority: int = 0,
                 request_id: Optional[str] = None, trace=None):
        self.inputs = inputs
        self.rows = rows
        self.deadline = deadline          # time.monotonic() absolute, or None
        self.trace = trace
        if not 0 <= int(priority) < _N_PRIORITIES:
            raise ServingError("priority must be 0 (interactive) or 1 "
                               "(batch), got %r" % (priority,))
        self.priority = int(priority)
        self.request_id = request_id
        self.submitted = time.monotonic()
        self.latency_ms: Optional[float] = None
        self._event = threading.Event()
        self._outputs: Optional[List[np.ndarray]] = None
        self._error: Optional[BaseException] = None

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    def set_result(self, outputs: List[np.ndarray]):
        self.latency_ms = (time.monotonic() - self.submitted) * 1e3
        self._outputs = outputs
        self._event.set()

    def set_error(self, err: BaseException):
        self.latency_ms = (time.monotonic() - self.submitted) * 1e3
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def get(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block for the result (``timeout`` in seconds). Raises the
        request's ServingError on failure."""
        if not self._event.wait(timeout):
            raise ServingError("result not ready after %.3fs" % timeout,
                               "wait_timeout")
        if self._error is not None:
            raise self._error
        return self._outputs


class BatchFormer:
    """Bounded request queue + micro-batch former.

    ``submit`` is the backpressure point: a full queue rejects immediately
    (the caller sheds load or retries) rather than buffering unboundedly,
    and a request whose deadline is already infeasible — given queued rows
    and the recent dispatch-latency EWMA fed by ``note_dispatch`` — is
    rejected at submit time with ``deadline_exceeded`` instead of being
    queued only to expire in the FIFO (reject-early beats queue-and-expire
    under overload: the client learns NOW, and the queue carries only work
    that can still meet its deadline).
    ``next_batch`` is the worker side: blocks for traffic, then holds the
    window open up to ``max_delay_ms`` past the OLDEST queued request's
    arrival while rows accumulate toward ``max_batch``. Expired requests
    are failed (``deadline_exceeded``) at pop time and do not poison the
    batch — the queue keeps draining.

    Priority/QoS: two admission classes (``Request.priority`` —
    interactive 0, batch 1). The former packs interactive requests first;
    batch-class requests ride only in the remaining row budget. Each class
    keeps FIFO order internally, and the delay window still opens from the
    oldest queued request regardless of class, so batch work is deferred
    under load but never starved while the queue drains.
    """

    def __init__(self, max_batch: int, max_delay_ms: float = 2.0,
                 queue_depth: int = 256, error_hook=None,
                 buckets_fn=None, coalesce_fill: float = 0.0):
        if max_batch < 1 or queue_depth < 1:
            raise ServingError("max_batch and queue_depth must be >= 1")
        if not 0.0 <= float(coalesce_fill) <= 1.0:
            raise ServingError("coalesce_fill must be in [0, 1]")
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self._error_hook = error_hook  # called with the code of each failure
        # cross-bucket coalescing: pack toward the LARGEST ladder bucket
        # that the queued rows fill to >= coalesce_fill, instead of packing
        # max_batch rows and letting dispatch pad to whatever bucket the
        # total lands in. buckets_fn returns the live ladder (it changes
        # under adaptive tuning); coalesce_fill == 0 disables the policy.
        self._buckets_fn = buckets_fn
        self.coalesce_fill = float(coalesce_fill)
        # one FIFO per priority class; all guarded by _cond
        self._qs = tuple(deque() for _ in range(_N_PRIORITIES))
        self._rows = 0  # queued rows (cached sum over self._qs)
        self._cond = threading.Condition()
        self._closed = False
        self._close_code = "shutdown"  # what post-close submits raise
        # reject-early feasibility estimate: EWMA of recent dispatch
        # service time (seconds per micro-batch), fed by note_dispatch
        # from the server's dispatch tail; parallelism = replica count
        # (concurrent dispatches divide the backlog drain time)
        self._ewma_batch_s = 0.0
        self._ewma_n = 0
        self.parallelism = 1

    def _fail(self, req: Request, err: ServingError):
        req.set_error(err)
        if self._error_hook is not None:
            self._error_hook(err.code)
        # observability tail (callers invoke _fail OUTSIDE _cond): a
        # failed request still gets a serving.queued span so its flight
        # timeline is complete, and a missed deadline snapshots a
        # diagnostic bundle — the SLO anomaly this queue exists to avoid
        if req.trace is not None and telemetry.enabled("serving"):
            telemetry.complete("serving.queued", domain="serving",
                               start_ns=int(req.submitted * 1e9),
                               rows=req.rows, error=err.code,
                               **req.trace.child().stamps())
        _flight.request_end(req.trace, ok=False, code=err.code,
                            latency_ms=req.latency_ms,
                            request_id=req.request_id)
        if err.code == "deadline_exceeded":
            _flight.on_anomaly("deadline_miss", req.trace,
                               request_id=req.request_id,
                               latency_ms=req.latency_ms,
                               message=str(err))

    def note_dispatch(self, seconds: float):
        """Feed one observed dispatch service time (seconds from batch
        handoff to results published) into the reject-early EWMA. Called
        by the server's dispatch tail from an engine worker — a leaf-style
        touch of ``_cond`` with nothing else held."""
        if seconds < 0:
            return
        with self._cond:
            if self._ewma_n == 0:
                self._ewma_batch_s = float(seconds)
            else:
                self._ewma_batch_s += 0.2 * (float(seconds)
                                             - self._ewma_batch_s)
            self._ewma_n += 1

    def dispatch_ewma_s(self) -> float:
        """Recent dispatch service-time estimate (0.0 until warmed)."""
        with self._cond:
            return self._ewma_batch_s if self._ewma_n else 0.0

    def _eta_s_locked(self, rows: int) -> Optional[float]:
        """Estimated seconds until a request of ``rows`` submitted NOW
        would finish dispatching, or None when the EWMA isn't warm yet
        (< 3 samples — never reject on a cold estimate). Caller holds
        ``_cond``."""
        if self._ewma_n < 3:
            return None
        backlog = self._rows + rows
        batches = -(-backlog // self.max_batch)  # ceil
        return batches * self._ewma_batch_s / max(1, self.parallelism)

    def submit(self, req: Request):
        if req.rows > self.max_batch:
            raise ServingError(
                "request of %d rows exceeds max_batch (%d); split it or "
                "raise the largest bucket" % (req.rows, self.max_batch),
                "too_large")
        now = time.monotonic()
        with self._cond:
            if self._closed:
                raise ServingError(
                    "server is shut down" if self._close_code == "shutdown"
                    else "server is draining for shutdown",
                    self._close_code)
            depth = sum(len(q) for q in self._qs)
            if depth >= self.queue_depth:
                raise ServingError(
                    "queue full (%d requests; MXNET_SERVING_QUEUE_DEPTH)"
                    % depth, "queue_full")
            if req.deadline is not None:
                # reject-early: never enqueue work that cannot make its
                # deadline given the queued-rows backlog and the recent
                # dispatch EWMA. Gated on a WARM estimate (>= 3 samples):
                # a cold former keeps the historical pop-time expiry path
                # so the contract is unchanged until real latencies exist.
                eta = self._eta_s_locked(req.rows)
                if eta is not None and now + eta >= req.deadline:
                    raise ServingError(
                        "deadline infeasible at submit: ~%.1f ms of queued "
                        "work ahead, %.1f ms budget left"
                        % (eta * 1e3, (req.deadline - now) * 1e3),
                        "deadline_exceeded")
            self._qs[req.priority].append(req)
            self._rows += req.rows
            self._cond.notify()

    def depth(self) -> int:
        """Queued (not yet dispatched) request count — the live gauge."""
        with self._cond:
            return sum(len(q) for q in self._qs)

    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self, code: str = "shutdown"):
        """Stop admitting; wake the former loop so it can drain and exit.
        ``code`` is what later submits raise (``"shutting_down"`` during
        a graceful drain, ``"shutdown"`` once stopped)."""
        with self._cond:
            self._closed = True
            self._close_code = code
            self._cond.notify_all()

    def fail_pending(self, code: str = "shutdown",
                     msg: str = "server stopped with the request queued"):
        """Fail every queued request (post-close, non-draining stop)."""
        with self._cond:
            pending = [r for q in self._qs for r in q]
            for q in self._qs:
                q.clear()
            self._rows = 0
        for r in pending:
            self._fail(r, ServingError(msg, code))

    def _pack_target(self, ladder) -> int:
        """Row target for the batch about to be packed (caller holds
        ``_cond``; ``ladder`` was snapshotted BEFORE the lock — the
        buckets callback reaches into server state and must not run
        under ``_cond``, the PR 2 ABBA contract). Plain forming packs
        toward max_batch; with coalescing on, pick the largest ladder
        bucket the queued rows fill to >= ``coalesce_fill`` — e.g. 5
        queued single rows on ladder (1, 4, 8) at fill 1.0 dispatch as
        a FULL bucket-4 batch plus a bucket-1 batch, instead of one
        5-row batch padded to 8. When no bucket meets the fill bar the
        window has already expired, so everything queued goes now
        (max_batch) and dispatch pads as before."""
        if not ladder or self.coalesce_fill <= 0:
            return self.max_batch
        eligible = [b for b in ladder
                    if self._rows >= self.coalesce_fill * b]
        return max(eligible) if eligible else self.max_batch

    def next_batch(self) -> Optional[List[Request]]:
        """Form the next micro-batch (>= 1 request, <= max_batch rows).
        Returns None when closed and fully drained."""
        while True:
            expired: List[Request] = []
            # ladder snapshot for coalescing, read OUTSIDE _cond: the
            # callback reads server state and a stale-by-one-swap ladder
            # only changes the advisory pack target
            ladder = self._buckets_fn() if (
                self._buckets_fn is not None and self.coalesce_fill > 0
            ) else None
            with self._cond:
                while not any(self._qs) and not self._closed:
                    self._cond.wait()
                if not any(self._qs) and self._closed:
                    return None
                # hold the window open from the OLDEST head request's
                # arrival regardless of class (a queued batch-class request
                # still bounds its wait); closed => dispatch immediately
                t_end = min(q[0].submitted for q in self._qs if q) \
                    + self.max_delay
                while (self._rows < self.max_batch and not self._closed):
                    remaining = t_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                target = self._pack_target(ladder)
                batch, rows, now, full = [], 0, time.monotonic(), False
                # admission order: interactive class drains first; batch
                # class rides in the leftover row budget (FIFO per class).
                # The first non-fitting head stops packing entirely — a
                # lower class must not slip past it into this micro-batch
                # (priority inversion); the next micro-batch takes it.
                for q in self._qs:
                    while q and not full:
                        req = q[0]
                        if req.expired(now):
                            q.popleft()
                            self._rows -= req.rows
                            expired.append(req)
                            continue
                        if rows + req.rows > target and batch:
                            full = True
                            break  # next micro-batch takes it
                        q.popleft()
                        self._rows -= req.rows
                        batch.append(req)
                        rows += req.rows
                    if full:
                        break
            # fail outside _cond: the error hook may take other locks
            # (e.g. ServingMetrics._lock, whose holder may call depth())
            for req in expired:
                self._fail(req, ServingError(
                    "deadline exceeded after %.1f ms in queue"
                    % ((now - req.submitted) * 1e3), "deadline_exceeded"))
            if batch:
                return batch
            # every popped request had expired: go back to waiting
