"""Route-level request/response logic for the HTTP front-end.

Pure functions between the wire and ``InferenceServer`` — the HTTP
handler (``frontend.server``) owns sockets and headers, this module owns
parsing, validation, and the ``ServingError`` code -> HTTP status map,
so every mapping is unit-testable without a socket.

Status vocabulary (docs/deployment.md "HTTP front-end"):

===================  =====================  ============================
ServingError code    at submit / admission  mid-flight (result wait)
===================  =====================  ============================
queue_full           429 + Retry-After      —
shed                 429 + Retry-After      —
deadline_exceeded    429 + Retry-After      504 (expired in queue)
too_large            413                    —
overloaded           503 + Retry-After      —
shutting_down        503 + Retry-After      503
shutdown             503                    503
dispatch_error       —                      500
wait_timeout         —                      504
cancelled            —                      499 (client closed)
===================  =====================  ============================

A submit-time ``deadline_exceeded`` is BACKPRESSURE (the reject-early
feasibility check said "retry later or relax the deadline") so it maps
to 429; once a request is admitted, the same code means the deadline
genuinely passed — a timeout, 504.
"""
from __future__ import annotations

import base64
import binascii
import json
from typing import Dict, Optional, Tuple

import numpy as np

from ..batcher import ServingError

#: codes that carry a Retry-After header on the rejection
RETRYABLE_CODES = frozenset(
    {"queue_full", "shed", "deadline_exceeded", "overloaded",
     "shutting_down"})

_SUBMIT_STATUS = {
    "queue_full": 429,
    "shed": 429,
    "deadline_exceeded": 429,
    "too_large": 413,
    "overloaded": 503,
    "shutting_down": 503,
    "shutdown": 503,
}

_RESULT_STATUS = {
    "deadline_exceeded": 504,
    "wait_timeout": 504,
    "shutting_down": 503,
    "shutdown": 503,
    "dispatch_error": 500,
    "cancelled": 499,
}


def status_for_error(code: str, submit_time: bool) -> int:
    """HTTP status for a structured ServingError code. Unknown codes are
    a server-side defect -> 500 (never let a new code turn into a silent
    200)."""
    table = _SUBMIT_STATUS if submit_time else _RESULT_STATUS
    return table.get(code, 400 if submit_time else 500)


def error_body(code: str, message: str, request_id: str,
               trace_id: Optional[str] = None) -> dict:
    """Structured error payload. When the request arrived with (or was
    assigned) a trace context, the trace id is echoed so the caller can
    pull the request's span tree from ``GET /debug/requests/<id>``."""
    body = {"error": {"code": code, "message": message},
            "request_id": request_id}
    if trace_id:
        body["trace_id"] = trace_id
    return body


class BadRequest(Exception):
    """Malformed client input -> 400 with a structured body."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


def parse_json_body(raw: bytes) -> dict:
    if not raw:
        raise BadRequest("empty body (expected a JSON object)")
    try:
        body = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise BadRequest("invalid JSON body: %s" % e)
    if not isinstance(body, dict):
        raise BadRequest("body must be a JSON object")
    return body


def parse_timeout_ms(header_val: Optional[str],
                     body: dict) -> Optional[float]:
    """Per-request deadline: the ``timeout-ms`` header wins over the
    body's ``timeout_ms`` (a header is what proxies and gateways can
    stamp without parsing the payload); None = server default."""
    raw = header_val if header_val is not None else body.get("timeout_ms")
    if raw is None:
        return None
    try:
        t = float(raw)
    except (TypeError, ValueError):
        raise BadRequest("timeout-ms must be a number, got %r" % (raw,))
    if t <= 0:
        raise BadRequest("timeout-ms must be > 0, got %g" % t)
    return t


def parse_priority(header_val: Optional[str], body: dict) -> int:
    """QoS class: ``x-priority`` header or body ``priority`` —
    ``interactive`` (default, 0) | ``batch`` (1)."""
    raw = header_val if header_val is not None else body.get("priority")
    if raw is None:
        return 0
    name = str(raw).strip().lower()
    if name in ("interactive", "0"):
        return 0
    if name in ("batch", "1"):
        return 1
    raise BadRequest("x-priority must be 'interactive' or 'batch', "
                     "got %r" % (raw,))


def parse_predict_inputs(body: dict) -> Dict[str, np.ndarray]:
    """``{"inputs": {name: value}}`` -> float32 arrays (a leading batch
    axis is the submit() contract, validated server-side).

    Two value forms: a nested JSON list, or the raw-tensor form
    ``{"b64": <base64 of the C-order buffer>, "shape": [...],
    "dtype": "float32"}`` — JSON float parsing costs ~6 ms for a
    canonical 33x512 request while base64+frombuffer stays ~50 us, so
    the raw form is what keeps the HTTP hop inside the <10%-of-batch-
    latency bench gate at realistic request sizes."""
    inputs = body.get("inputs")
    if not isinstance(inputs, dict) or not inputs:
        raise BadRequest('body must carry {"inputs": {name: array}}')
    feed = {}
    for name, val in inputs.items():
        try:
            if isinstance(val, dict):
                raw = base64.b64decode(val["b64"])
                arr = np.frombuffer(raw, dtype=np.dtype(
                    str(val.get("dtype", "float32"))))
                feed[str(name)] = arr.reshape(
                    [int(d) for d in val["shape"]]).astype(
                        np.float32, copy=False)
            else:
                feed[str(name)] = np.asarray(val, dtype=np.float32)
        except (KeyError, ValueError, TypeError, binascii.Error) as e:
            raise BadRequest("input %r is not array-like: %s" % (name, e))
    return feed


def parse_generate_body(body: dict) -> Tuple[list, Optional[int], float,
                                             Optional[int]]:
    """-> (prompt, max_new_tokens, temperature, seed)."""
    prompt = body.get("prompt")
    if not isinstance(prompt, (list, tuple)) or not prompt:
        raise BadRequest('body must carry {"prompt": [token ids]}')
    try:
        prompt = [int(t) for t in prompt]
    except (TypeError, ValueError):
        raise BadRequest("prompt must be a list of integer token ids")
    max_new = body.get("max_new_tokens")
    if max_new is not None:
        try:
            max_new = int(max_new)
        except (TypeError, ValueError):
            raise BadRequest("max_new_tokens must be an integer")
    try:
        temperature = float(body.get("temperature", 0.0))
    except (TypeError, ValueError):
        raise BadRequest("temperature must be a number")
    seed = body.get("seed")
    if seed is not None:
        try:
            seed = int(seed)
        except (TypeError, ValueError):
            raise BadRequest("seed must be an integer")
    return prompt, max_new, temperature, seed


def predict_response(req_outputs, request_id: str,
                     encoding: str = "json") -> dict:
    """``encoding="b64"`` (the request's ``"encoding"`` field) returns
    each output as the raw-tensor dict instead of a nested list —
    symmetric with the b64 input form and off the JSON float-serialize
    path for large outputs."""
    if encoding == "b64":
        outs = []
        for o in req_outputs:
            a = np.ascontiguousarray(o)
            outs.append({"b64": base64.b64encode(a).decode("ascii"),
                         "shape": list(a.shape), "dtype": str(a.dtype)})
        return {"request_id": request_id, "outputs": outs}
    return {"request_id": request_id,
            "outputs": [np.asarray(o).tolist() for o in req_outputs]}


def wait_budget_s(timeout_ms: Optional[float], default_ms: float) -> float:
    """Result-wait budget: the request deadline plus grace, so a request
    failed by the former surfaces its structured code rather than a
    blunt wait_timeout (mirrors InferenceServer.predict)."""
    t = default_ms if timeout_ms is None else timeout_ms
    return (t / 1e3 + 60.0) if t and t > 0 else 3600.0


def serving_error(e: BaseException) -> ServingError:
    """Normalize any dispatch-side exception to a structured error."""
    if isinstance(e, ServingError):
        return e
    return ServingError("%s: %s" % (type(e).__name__, e), "dispatch_error")
