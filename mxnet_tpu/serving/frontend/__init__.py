"""mxnet_tpu.serving.frontend — the HTTP/1.1 network front-end.

The socket over the in-process ``InferenceServer`` (ROADMAP "Network
front-end + production serving scale-out"): stdlib-only HTTP serving
with SSE token streaming, admission control (429/503 + Retry-After),
deadline propagation, interactive/batch QoS classes, Prometheus
``/metrics``, ``/healthz``/``/readyz``, and SIGTERM graceful drain.

    from mxnet_tpu import serving
    from mxnet_tpu.serving.frontend import FrontendConfig, HttpFrontend

    srv = serving.create_server("ckpt/m", epoch=1,
                                example_shapes={"data": (3, 224, 224)})
    fe = HttpFrontend(srv, FrontendConfig(port=8080))
    fe.install_signal_handlers()      # SIGTERM -> zero-drop drain
    fe.start(wait_ready=True)
    fe.serve_forever()

Protocol details and curl examples: docs/deployment.md "HTTP
front-end"; a runnable client lives in examples/http-serving/.
"""
from .admission import AdmissionController, AdmissionDecision
from .routes import BadRequest, status_for_error
from .server import FrontendConfig, HttpFrontend
from .sse import SSE_CONTENT_TYPE, iter_sse, sse_event

__all__ = [
    "AdmissionController", "AdmissionDecision", "BadRequest",
    "FrontendConfig", "HttpFrontend", "SSE_CONTENT_TYPE", "iter_sse",
    "sse_event", "status_for_error",
]
