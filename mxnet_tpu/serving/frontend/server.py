"""HTTP/1.1 front-end over the in-process ``InferenceServer``.

Dependency-free network serving (stdlib ``http.server`` threading
server — one OS thread per connection, which is the right shape here
because every handler blocks on a ``Request``/``TokenStream`` future
while the real work runs on the engine's worker pool):

- ``POST /v1/predict``   -> ``InferenceServer.submit()`` + wait; JSON in
  (``{"inputs": {...}}``), JSON out (``{"request_id", "outputs"}``)
- ``POST /v1/generate``  -> ``submit_stream()``; SSE token stream
  (default) or one JSON body with ``"stream": false``
- ``GET  /metrics``      -> the telemetry registry's Prometheus
  exposition, served with ``telemetry.CONTENT_TYPE_LATEST``
- ``GET  /healthz``      -> process liveness (always 200 while serving)
- ``GET  /readyz``       -> 200 only once every replica's bucket ladder
  is compiled/progcache-warm AND the server is not draining — the
  rolling-restart gate: traffic admitted now never stalls on a compile

Production behavior on top of the transport (docs/deployment.md):
admission control (429/503 + ``Retry-After``; ``frontend.admission``),
per-request deadlines from the ``timeout-ms`` header feeding the
batcher's reject-early feasibility check, ``x-priority``
interactive/batch QoS classes mapped onto batcher admission order, a
``request_id`` (``x-request-id`` or generated) echoed in every response
and annotated on the ``serving.http.request`` span, and SIGTERM
graceful drain through ``InferenceServer.stop(drain=True)`` — in-flight
requests and SSE streams all complete; only NEW work is refused.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ... import telemetry
from ...telemetry import context as trace_context
from ...telemetry import flight as _flight
from ..batcher import ServingError
from . import routes
from .admission import AdmissionController
from .sse import SSE_CONTENT_TYPE, sse_event

_log = logging.getLogger("mxnet_tpu")


@dataclass
class FrontendConfig:
    """Socket + admission knobs (``MXNET_HTTP_*`` env defaults read at
    construction; docs/env_var.md)."""
    host: str = field(default_factory=lambda: os.environ.get(
        "MXNET_HTTP_HOST", "127.0.0.1"))
    #: listen port; 0 = ephemeral (tests — read it back from ``.port``)
    port: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_HTTP_PORT", "8080")))
    #: hard cap on concurrently-handled requests (503 above it)
    max_inflight: int = field(default_factory=lambda: int(
        os.environ.get("MXNET_HTTP_MAX_INFLIGHT", "64")))
    #: batch-class shed threshold, percent of the batcher queue_depth
    shed_pct: float = field(default_factory=lambda: float(
        os.environ.get("MXNET_HTTP_SHED_PCT", "80")))


class _Httpd(ThreadingHTTPServer):
    # socketserver's default listen backlog of 5 RSTs simultaneous
    # connects the moment a burst outruns the accept loop — an overload
    # burst must shed with a 429/503, never a connection reset
    request_queue_size = 128


class _Handler(BaseHTTPRequestHandler):
    """Per-connection handler; the owning HttpFrontend hangs off the
    ThreadingHTTPServer instance (``self.server.frontend``)."""

    protocol_version = "HTTP/1.1"
    # HTTP/1.1 keep-alive: JSON responses carry Content-Length; SSE
    # responses set Connection: close and close_connection explicitly
    # TCP_NODELAY: headers and body flush as separate writes, and Nagle
    # + delayed-ACK turns that into a ~40 ms stall per response on
    # loopback; SSE token latency needs immediate segments anyway
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        _log.debug("http: %s", fmt % args)

    # --- plumbing ---------------------------------------------------------
    @property
    def fe(self) -> "HttpFrontend":
        return self.server.frontend

    def _request_id(self) -> str:
        return self.headers.get("x-request-id") or \
            trace_context.mint_request_id()

    def _send_json(self, status: int, payload: dict, request_id: str,
                   retry_after_s: Optional[int] = None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("x-request-id", request_id)
        ctx = getattr(self, "_trace", None)
        if ctx is not None:
            self.send_header("x-trace-id", ctx.trace_id)
            self.send_header("traceparent",
                             trace_context.to_traceparent(ctx))
        if retry_after_s is not None:
            self.send_header("Retry-After", str(int(retry_after_s)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, status: int, code: str, message: str,
                         request_id: str,
                         retry_after_s: Optional[int] = None):
        if retry_after_s is None and code in routes.RETRYABLE_CODES:
            retry_after_s = 1
        ctx = getattr(self, "_trace", None)
        self._send_json(status,
                        routes.error_body(
                            code, message, request_id,
                            trace_id=(ctx.trace_id if ctx is not None
                                      else None)),
                        request_id, retry_after_s)

    def _read_body(self) -> bytes:
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = 0
        return self.rfile.read(length) if length > 0 else b""

    # --- GET --------------------------------------------------------------
    def do_GET(self):
        self._trace = None  # keep-alive: don't leak a prior POST's trace
        rid = self._request_id()
        if self.path == "/healthz":
            self._send_json(200, {"status": "ok"}, rid)
        elif self.path == "/readyz":
            if self.fe.ready():
                self._send_json(200, {"status": "ready"}, rid)
            else:
                reason = ("draining" if self.fe.admission.draining()
                          else "warming")
                self._send_json(503, {"status": reason}, rid,
                                retry_after_s=1)
        elif self.path == "/metrics":
            body = telemetry.registry.exposition().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", telemetry.CONTENT_TYPE_LATEST)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/debug/requests/"):
            # one request's assembled span tree, by request_id or
            # trace_id — the landing page of an exemplar / error echo
            ident = self.path[len("/debug/requests/"):]
            tree = _flight.request_tree(ident) if ident else None
            if tree is None:
                self._send_error_json(404, "not_found",
                                      "no recorded request %r" % ident,
                                      rid)
            else:
                self._send_json(200, tree, rid)
        elif self.path == "/debug/flight":
            self._send_json(200, _flight.summary(), rid)
        else:
            self._send_error_json(404, "not_found",
                                  "no route %r" % self.path, rid)

    # --- POST -------------------------------------------------------------
    def do_POST(self):
        # trace context is minted (or continued from a W3C traceparent
        # header) at the network edge, installed on this handler thread,
        # and rides the Request/TokenStream through batcher + scheduler —
        # every span below stamps the same trace_id (docs/observability.md
        # "Request tracing")
        ctx = trace_context.from_headers(self.headers)
        self._trace = ctx
        rid = ctx.request_id
        if self.path not in ("/v1/predict", "/v1/generate"):
            self._send_error_json(404, "not_found",
                                  "no route %r" % self.path, rid)
            return
        raw = self._read_body()
        route = self.path.rsplit("/", 1)[-1]
        with trace_context.use(ctx), \
                telemetry.span("serving.http.request", domain="serving",
                               route=route, **ctx.stamps()) as sp:
            try:
                body = routes.parse_json_body(raw)
                priority = routes.parse_priority(
                    self.headers.get("x-priority"), body)
                timeout_ms = routes.parse_timeout_ms(
                    self.headers.get("timeout-ms"), body)
            except routes.BadRequest as e:
                self._send_error_json(400, "bad_request", e.message, rid)
                return
            decision, _n = self.fe.admission.decide(priority)
            if decision is not None:
                sp.annotate(shed=decision.code)
                self._send_error_json(decision.status, decision.code,
                                      decision.message, rid,
                                      decision.retry_after_s)
                return
            try:  # admitted: paired exit() in finally
                if self.path == "/v1/predict":
                    self._predict(body, priority, timeout_ms, rid, sp)
                else:
                    self._generate(body, priority, timeout_ms, rid, sp)
            finally:
                self.fe.admission.exit()

    def _predict(self, body: dict, priority: int,
                 timeout_ms: Optional[float], rid: str, sp):
        srv = self.fe.server
        try:
            feed = routes.parse_predict_inputs(body)
        except routes.BadRequest as e:
            self._send_error_json(400, "bad_request", e.message, rid)
            return
        try:
            req = srv.submit(timeout_ms=timeout_ms, priority=priority,
                             request_id=rid, **feed)
        except ServingError as e:
            self._send_error_json(routes.status_for_error(e.code, True),
                                  e.code, str(e), rid)
            return
        try:
            outs = req.get(routes.wait_budget_s(
                timeout_ms, srv.config.timeout_ms))
        except ServingError as e:
            self._send_error_json(routes.status_for_error(e.code, False),
                                  e.code, str(e), rid)
            return
        sp.annotate(rows=req.rows, latency_ms=req.latency_ms)
        enc = "b64" if body.get("encoding") == "b64" else "json"
        self._send_json(200, routes.predict_response(outs, rid, enc), rid)

    def _generate(self, body: dict, priority: int,
                  timeout_ms: Optional[float], rid: str, sp):
        srv = self.fe.server
        try:
            prompt, max_new, temperature, seed = \
                routes.parse_generate_body(body)
        except routes.BadRequest as e:
            self._send_error_json(400, "bad_request", e.message, rid)
            return
        want_stream = body.get("stream")
        if want_stream is None:  # default SSE unless the client asked
            want_stream = "application/json" not in \
                self.headers.get("Accept", "")
        try:
            stream = srv.submit_stream(prompt, max_new,
                                       timeout_ms=timeout_ms,
                                       temperature=temperature, seed=seed,
                                       request_id=rid)
        except ServingError as e:
            self._send_error_json(routes.status_for_error(e.code, True),
                                  e.code, str(e), rid)
            return
        if not want_stream:
            try:
                toks = stream.tokens(routes.wait_budget_s(timeout_ms, 0))
            except ServingError as e:
                self._send_error_json(
                    routes.status_for_error(e.code, False), e.code,
                    str(e), rid)
                return
            sp.annotate(tokens=len(toks),
                        finish_reason=stream.finish_reason)
            self._send_json(200, {"request_id": rid, "tokens": toks,
                                  "finish_reason": stream.finish_reason},
                            rid)
            return
        # SSE: status goes out before tokens exist, so mid-stream
        # failures travel in-band as an `error` event; Connection: close
        # delimits the stream (no Content-Length on a live stream)
        self.send_response(200)
        self.send_header("Content-Type", SSE_CONTENT_TYPE)
        self.send_header("Cache-Control", "no-cache")
        self.send_header("x-request-id", rid)
        ctx = getattr(self, "_trace", None)
        if ctx is not None:
            self.send_header("x-trace-id", ctx.trace_id)
            self.send_header("traceparent",
                             trace_context.to_traceparent(ctx))
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        n = 0
        try:
            try:
                for tok in stream:
                    self.wfile.write(sse_event(
                        "token", {"token": tok, "index": n}))
                    self.wfile.flush()
                    n += 1
            except ServingError as e:
                sp.annotate(tokens=n, error=e.code)
                evt = {"code": e.code, "message": str(e),
                       "request_id": rid}
                if ctx is not None:
                    evt["trace_id"] = ctx.trace_id
                self.wfile.write(sse_event("error", evt))
                self.wfile.flush()
                return
            sp.annotate(tokens=n, finish_reason=stream.finish_reason)
            self.wfile.write(sse_event(
                "done", {"finish_reason": stream.finish_reason,
                         "tokens": n, "request_id": rid}))
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream: free the decode slot now
            stream.cancel()
            sp.annotate(tokens=n, error="client_disconnected")


class HttpFrontend:
    """Owns the listening socket, its serve thread, background ladder
    warmup, and the drain choreography. ``server`` is a (started or not)
    ``InferenceServer``; ``start()`` starts it if needed."""

    def __init__(self, server, config: Optional[FrontendConfig] = None):
        self.server = server
        self.config = config or FrontendConfig()
        self.admission = AdmissionController(
            server, max_inflight=self.config.max_inflight,
            shed_pct=self.config.shed_pct)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._warm_done = False
        self._stopped = threading.Event()
        self._stop_once = threading.Lock()
        self._stop_started = False

    # --- lifecycle --------------------------------------------------------
    def start(self, wait_ready: bool = False,
              ready_timeout_s: float = 120.0) -> "HttpFrontend":
        if self._httpd is not None:
            raise ServingError("frontend already started")
        if not self.server._started:
            self.server.start()
        httpd = _Httpd((self.config.host, self.config.port), _Handler)
        httpd.daemon_threads = True
        httpd.frontend = self
        self._httpd = httpd
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True, name="http-frontend")
        self._thread.start()
        # warm the ladder off-thread so the socket answers /healthz and
        # sheds load during warmup instead of hanging cold clients
        warm = threading.Thread(target=self._warm, daemon=True,
                                name="http-warmup")
        warm.start()
        if wait_ready:
            deadline = time.monotonic() + ready_timeout_s
            while not self.ready():
                if time.monotonic() >= deadline:
                    raise ServingError("frontend not ready within %gs"
                                       % ready_timeout_s)
                time.sleep(0.01)
        return self

    def _warm(self):
        try:
            self.server.warm()
        except BaseException:
            _log.exception("http frontend ladder warmup failed")
        finally:
            self._warm_done = True

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise ServingError("frontend not started")
        return self._httpd.server_address[1]

    def ready(self) -> bool:
        """The /readyz predicate: warm, started, and not draining."""
        return (self._warm_done and not self.admission.draining()
                and self.server.ready())

    def stop(self, drain: bool = True,
             deadline_ms: Optional[float] = None):
        """Drain and stop: flip admission to draining (new requests get
        503 + Retry-After, /readyz goes unready so balancers stop
        routing here), let the inference server finish everything queued
        (``stop(drain=True)`` — in-flight SSE streams run to their
        natural finish), wait for the last handler to flush, then close
        the socket. Idempotent; safe from a signal-handler thread."""
        with self._stop_once:
            already = self._stop_started
            self._stop_started = True
        if already:  # second stopper: just wait out the first (no hold)
            self._stopped.wait()
            return
        self.admission.set_draining()
        try:
            self.server.stop(drain=drain, deadline_ms=deadline_ms)
            # handlers past admission are still streaming results out;
            # give them until the drain deadline (default: as long as
            # they need — their futures have already resolved)
            limit = None if deadline_ms is None \
                else time.monotonic() + deadline_ms / 1e3
            while self.admission.inflight() > 0:
                if limit is not None and time.monotonic() >= limit:
                    break
                time.sleep(0.005)
        finally:
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
                self._thread.join()
            self._stopped.set()

    def serve_forever(self):
        """Block the calling thread until ``stop()`` completes (the
        subprocess entry point: install_signal_handlers + serve_forever
        is a whole server process)."""
        self._stopped.wait()

    def install_signal_handlers(self, signals=(signal.SIGTERM,)):
        """SIGTERM = rolling-restart drain: handlers must return
        immediately, so the drain runs on a daemon thread. Main-thread
        only (CPython signal delivery contract)."""
        def _drain(signum, frame):
            _log.info("http frontend: signal %d -> graceful drain",
                      signum)
            threading.Thread(target=self.stop, kwargs={"drain": True},
                             daemon=True, name="http-drain").start()
        for s in signals:
            signal.signal(s, _drain)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=not any(exc))
