"""Admission control for the HTTP front-end.

Reject-early at the socket: a request that cannot be served — the
process is draining, the handler pool is saturated, or the batcher queue
is deep enough that batch-class work would only expire in the FIFO —
is answered immediately with 429/503 + ``Retry-After`` instead of being
queued into a timeout. This is the transport-level half of the policy;
the submit-time deadline-feasibility check lives in
``BatchFormer.submit`` (reject-early beats queue-and-expire).

Policies, in evaluation order:

1. draining (SIGTERM received)      -> 503 ``shutting_down``
2. in-flight >= ``max_inflight``    -> 503 ``overloaded``
   (``MXNET_HTTP_MAX_INFLIGHT`` — bounds handler threads + held results)
3. batch-class AND backlog >= ``shed_pct``% of the batcher's
   ``queue_depth``                  -> 429 ``shed``
   (``MXNET_HTTP_SHED_PCT`` — interactive traffic keeps the headroom
   between ``shed_pct`` and 100%, where ``queue_full`` takes over)

The shed signal counts the WHOLE pending pipeline, not just the former
deque: the former pipelines batches into the engine asynchronously
(``engine.push_async``), so under sustained overload the former drains
instantly and the backlog accumulates as outstanding engine ops on the
replica variables — ``former.depth()`` alone reads ~0 exactly when the
server is drowning. Backlog = queued requests + in-flight dispatched
batches (``server.router_inflight()``).

``Retry-After`` is estimated from that backlog times the recent
dispatch EWMA (minimum 1s) — an honest hint, not a promise.

Lock discipline: ``_lock`` is a LEAF (rank 100, LOCK_HIERARCHY) — it
guards only the in-flight counter and draining flag; policy reads
(``former.depth()``, rank 50) happen strictly OUTSIDE the hold.
"""
from __future__ import annotations

import threading
import weakref
from typing import Optional, Tuple

from ... import telemetry
from ...telemetry import context as _trace_context
from ...telemetry import flight as _flight
from ..batcher import PRIORITY_BATCH


class AdmissionDecision:
    """A rejection: HTTP status + structured code + Retry-After hint."""

    __slots__ = ("status", "code", "message", "retry_after_s")

    def __init__(self, status: int, code: str, message: str,
                 retry_after_s: int):
        self.status = status
        self.code = code
        self.message = message
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Front-door gate shared by every handler thread."""

    def __init__(self, server, max_inflight: int, shed_pct: float):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        if not 0.0 < float(shed_pct) <= 100.0:
            raise ValueError("shed_pct must be in (0, 100]")
        self._server = server
        self.max_inflight = int(max_inflight)
        self.shed_pct = float(shed_pct)
        self._lock = threading.Lock()
        self._inflight = 0
        self._draining = False
        reg = telemetry.registry
        self._m_requests = reg.counter(
            "http_requests_total", help="HTTP requests accepted past "
            "admission (all routes)")
        self._m_shed = reg.counter(
            "http_shed_total", help="HTTP requests rejected by admission "
            "control (429/503)")
        # the gauge is process-global (get-or-create) while controllers
        # are per-frontend: bind the callback through a weakref and
        # re-point the existing gauge at the newest live controller
        wref = weakref.ref(self)

        def _inflight_now():
            c = wref()
            return c.inflight() if c is not None else 0.0

        reg.gauge("http_inflight",
                  help="HTTP requests currently being handled")._fn = \
            _inflight_now

    # --- in-flight accounting (leaf lock) --------------------------------
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def enter(self):
        with self._lock:
            self._inflight += 1

    def exit(self):
        with self._lock:
            self._inflight -= 1

    # --- drain flag -------------------------------------------------------
    def set_draining(self):
        with self._lock:
            already = self._draining
            self._draining = True
        if not already:
            # drain start is an SLO anomaly worth a bundle: it captures
            # the in-flight picture a rolling restart interrupts
            _flight.on_anomaly("drain", inflight=self.inflight())

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # --- the policy -------------------------------------------------------
    def _backlog(self) -> int:
        """Total pending work: requests queued in the former PLUS batches
        already dispatched but not yet completed (outstanding engine ops
        on the replica vars). The former hands batches to the engine
        asynchronously, so its own deque is near-empty under steady
        overload — the in-flight term is what actually measures
        saturation then. Servers without a router (unit-test stubs)
        contribute only the queued term."""
        former = self._server._former
        backlog = former.depth()
        inflight_fn = getattr(self._server, "router_inflight", None)
        if inflight_fn is not None:
            backlog += sum(inflight_fn())
        return backlog

    def _retry_after_s(self, backlog: Optional[int] = None) -> int:
        """Backlog-drain estimate: pending work x dispatch EWMA over
        the former's parallelism, floored at 1s."""
        former = self._server._former
        if backlog is None:
            backlog = self._backlog()
        eta = backlog * former.dispatch_ewma_s() \
            / max(1, former.parallelism)
        return max(1, int(eta + 0.999))

    def decide(self, priority: int) -> Tuple[Optional[AdmissionDecision],
                                             int]:
        """None = admitted (and counted in-flight — the caller MUST pair
        with ``exit()``); otherwise the rejection to send. Returns
        ``(decision, inflight_now)``."""
        with self._lock:
            if self._draining:
                decision = AdmissionDecision(
                    503, "shutting_down",
                    "server is draining for shutdown", 1)
                n = self._inflight
            elif self._inflight >= self.max_inflight:
                decision = AdmissionDecision(
                    503, "overloaded",
                    "%d requests in flight (MXNET_HTTP_MAX_INFLIGHT=%d)"
                    % (self._inflight, self.max_inflight), 0)
                n = self._inflight
            else:
                decision = None
                self._inflight += 1
                n = self._inflight
        if decision is None and priority == PRIORITY_BATCH:
            # backlog shed for the deferrable class, read OUTSIDE the
            # leaf lock (former._cond is rank 50)
            backlog = self._backlog()
            cap = self._server._former.queue_depth
            if backlog >= self.shed_pct / 100.0 * cap:
                with self._lock:
                    self._inflight -= 1
                    n = self._inflight
                decision = AdmissionDecision(
                    429, "shed",
                    "batch-class shed: backlog %d/%d >= %g%% "
                    "(MXNET_HTTP_SHED_PCT)" % (backlog, cap, self.shed_pct),
                    self._retry_after_s(backlog))
        if decision is None:
            self._m_requests.inc()
        else:
            self._m_shed.inc()
            if decision.retry_after_s == 0:
                decision.retry_after_s = self._retry_after_s()
            if decision.code == "shed":
                # bundle the moment load shedding kicks in (bounded by
                # MXNET_FLIGHT_MAX_BUNDLES, so a sustained storm writes
                # a handful, not one per rejected request)
                _flight.on_anomaly(
                    "shed", _trace_context.current_context(),
                    message=decision.message,
                    retry_after_s=decision.retry_after_s)
        return decision, n
