"""Server-Sent Events framing for the HTTP front-end.

The generate endpoint streams ``TokenStream`` tokens as SSE — the
simplest HTTP-native streaming transport (one long-lived response, no
framing library): each event is ``event: <name>\\ndata: <json>\\n\\n``.
The protocol this front-end speaks (docs/deployment.md):

- ``event: token``  ``data: {"token": <id>, "index": <n>}`` per token
- ``event: done``   terminal; ``data`` carries ``finish_reason``,
  ``request_id`` and the total token count
- ``event: error``  terminal; ``data`` carries the structured
  ``ServingError`` ``code`` + message (mid-stream failures cannot
  change the already-sent 200 status line, so they travel in-band)

``iter_sse`` is the matching parser — used by the test suite and the
example client, and a reference for any non-Python consumer.
"""
from __future__ import annotations

import json
from typing import Iterator, Tuple

#: SSE response content type (the other half of the framing contract;
#: metrics' is ``telemetry.CONTENT_TYPE_LATEST``)
SSE_CONTENT_TYPE = "text/event-stream"


def sse_event(event: str, data: dict) -> bytes:
    """One wire-ready SSE frame (compact JSON payload)."""
    return ("event: %s\ndata: %s\n\n"
            % (event, json.dumps(data, separators=(",", ":")))).encode()


def iter_sse(fp) -> Iterator[Tuple[str, dict]]:
    """Parse SSE frames from a binary file-like (e.g. the response of
    ``http.client`` / a socket makefile). Yields ``(event, data)`` pairs
    until EOF; tolerates comment lines (``:``) and multi-``data:``
    frames per the SSE spec (concatenated with newlines before the JSON
    parse)."""
    event, data_lines = "message", []
    for raw in fp:
        line = raw.decode("utf-8").rstrip("\r\n")
        if not line:                      # blank line terminates a frame
            if data_lines:
                yield event, json.loads("\n".join(data_lines))
            event, data_lines = "message", []
            continue
        if line.startswith(":"):          # comment / keep-alive
            continue
        field, _, value = line.partition(":")
        value = value[1:] if value.startswith(" ") else value
        if field == "event":
            event = value
        elif field == "data":
            data_lines.append(value)
    if data_lines:                        # EOF without trailing blank line
        yield event, json.loads("\n".join(data_lines))
