"""Serving metrics surface.

Follows metric.py's EvalMetric idiom — ``get()`` returns parallel
name/value lists, ``get_name_value()`` zips them, ``reset()`` rezeroes —
plus a batch-end callback hook in the callback.py Speedometer style: the
server invokes ``batch_end_callback(ServingBatchEndParam(...))`` after
every dispatched micro-batch.

Tracked: QPS, p50/p95/p99 request latency, mean batch occupancy (real
rows per dispatched batch), padding efficiency (real rows / padded bucket
rows — the cost of the fixed-shape discipline), live queue depth, and the
bucket cache's compile/hit/miss counters.
"""
from __future__ import annotations

import threading
import time
from collections import deque, namedtuple
from typing import Callable, Dict, List, Optional, Sequence

from .. import telemetry

ServingBatchEndParam = namedtuple(
    "ServingBatchEndParam",
    ["nbatch", "bucket", "rows", "replica", "latency_ms", "occupancy",
     "metrics"])
"""Passed to the server's batch_end_callback after each dispatched batch:
batch ordinal, bucket size used, real rows, replica index, mean request
latency of the batch (ms), rows (== occupancy of this batch), and the live
ServingMetrics object."""


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


#: registry latency histogram buckets (ms) — coarse SLO bands; the
#: fine-grained percentiles stay on the ServingMetrics windows
LATENCY_BUCKETS_MS = (1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)


def latency_histogram() -> "telemetry.Histogram":
    """The process-wide ``serving_request_latency_ms`` histogram. Unlike
    the windowed percentiles it is cumulative AND carries OpenMetrics
    exemplars: each bucket remembers the last ``trace_id`` observed into
    it, so a Prometheus latency bucket links to one concrete request
    timeline (``GET /debug/requests/<trace_id>``)."""
    return telemetry.registry.histogram(
        "serving_request_latency_ms", buckets=LATENCY_BUCKETS_MS,
        help="end-to-end serving request latency (ms), predict + generate")


class ServingMetrics:
    """Thread-safe serving counters with metric.py-style getters."""

    #: ring-buffer size for latency percentiles (recent-window, not
    #: whole-lifetime, so a warmup spike ages out)
    LATENCY_WINDOW = 4096
    #: per-bucket ring-buffer size (smaller: there is one per bucket)
    BUCKET_LATENCY_WINDOW = 1024

    def __init__(self, queue_depth_fn: Optional[Callable[[], int]] = None,
                 cache_stats_fn: Optional[Callable[[], Dict]] = None,
                 router_inflight_fn: Optional[
                     Callable[[], Sequence[int]]] = None,
                 ladder_version_fn: Optional[Callable[[], int]] = None):
        self._lock = threading.Lock()
        self._queue_depth_fn = queue_depth_fn
        self._cache_stats_fn = cache_stats_fn
        # router gauges (set by InferenceServer): per-replica in-flight op
        # counts and the current bucket-ladder version — like the queue
        # depth gauge these are READ BEFORE _lock in get() (they reach into
        # engine/server state that must never nest inside _lock)
        self._router_inflight_fn = router_inflight_fn
        self._ladder_version_fn = ladder_version_fn
        self.reset()
        # no longer a metrics island: the central registry adopts this
        # instance (weakref'd) so registry.exposition() carries every
        # serving gauge as serving_<name>{sid="..."} (docs/deployment.md)
        self.sid = telemetry.registry.register_group("serving", self)

    def reset(self):
        with self._lock:
            self._t0 = time.monotonic()
            self.n_submitted = 0
            self.n_completed = 0
            self.n_batches = 0
            self.sum_rows = 0
            self.sum_bucket_rows = 0
            self.errors: Dict[str, int] = {}
            self._lat: deque = deque(maxlen=self.LATENCY_WINDOW)
            # per-bucket latency windows + batch counts: the SLO seam —
            # tail latency is a property of a bucket (its compiled shape),
            # not of the mixed traffic aggregate
            self._bucket_lat: Dict[int, deque] = {}
            self._bucket_batches: Dict[int, int] = {}
            # request-size histogram (rows -> count): the BucketTuner's
            # input signal for adaptive ladder derivation
            self._size_hist: Dict[int, int] = {}

    # --- recorders (called by the server/batcher) -------------------------
    def record_submit(self, rows: int = 1):
        with self._lock:
            self.n_submitted += 1
            self._size_hist[rows] = self._size_hist.get(rows, 0) + 1

    def record_error(self, code: str):
        with self._lock:
            self.errors[code] = self.errors.get(code, 0) + 1

    def observe_latency(self, latency_ms: Optional[float],
                        trace_id: Optional[str] = None):
        """Feed one completed request into the registry latency
        histogram, attaching the request's trace id as the bucket's
        exemplar. Lock-free here — the histogram has its own leaf lock."""
        if latency_ms is not None:
            latency_histogram().observe(float(latency_ms),
                                        exemplar=trace_id)

    def record_batch(self, rows: int, bucket: int,
                     latencies_ms: Sequence[float]):
        with self._lock:
            self.n_batches += 1
            self.sum_rows += rows
            self.sum_bucket_rows += bucket
            self.n_completed += len(latencies_ms)
            self._lat.extend(latencies_ms)
            blat = self._bucket_lat.get(bucket)
            if blat is None:
                blat = self._bucket_lat[bucket] = deque(
                    maxlen=self.BUCKET_LATENCY_WINDOW)
            blat.extend(latencies_ms)
            self._bucket_batches[bucket] = \
                self._bucket_batches.get(bucket, 0) + 1

    # --- metric.py-style surface ------------------------------------------
    def get(self):
        """(names, values), EvalMetric.get() shape."""
        # read the gauges BEFORE taking _lock: depth() takes the former's
        # condition, and the former calls record_error (which takes _lock)
        # — nesting them here would order the locks ABBA; the router gauges
        # follow the same rule (they take engine._inflight_lock / read
        # server state)
        depth = self._queue_depth_fn() if self._queue_depth_fn else 0
        inflight = (list(self._router_inflight_fn())
                    if self._router_inflight_fn else [])
        ladder_version = (self._ladder_version_fn()
                          if self._ladder_version_fn else 0)
        with self._lock:
            dt = max(time.monotonic() - self._t0, 1e-9)
            lat = sorted(self._lat)
            names = ["qps", "latency_ms_p50", "latency_ms_p95",
                     "latency_ms_p99", "mean_batch_occupancy",
                     "padding_efficiency", "queue_depth", "requests",
                     "completed", "batches", "errors"]
            values = [
                self.n_completed / dt,
                _percentile(lat, 50), _percentile(lat, 95),
                _percentile(lat, 99),
                (self.sum_rows / self.n_batches) if self.n_batches
                else float("nan"),
                (self.sum_rows / self.sum_bucket_rows)
                if self.sum_bucket_rows else float("nan"),
                depth,
                self.n_submitted, self.n_completed, self.n_batches,
                sum(self.errors.values()),
            ]
            # padding_waste_pct: the complement of padding_efficiency in
            # percent — the headline the zero-copy/coalescing/tuning work
            # drives down (NaN until something dispatched)
            names.append("padding_waste_pct")
            values.append(
                100.0 * (1.0 - self.sum_rows / self.sum_bucket_rows)
                if self.sum_bucket_rows else float("nan"))
            names.append("bucket_ladder_version")
            values.append(ladder_version)
            for i, n in enumerate(inflight):
                names.append("router_inflight_replica%d" % i)
                values.append(n)
            # per-bucket gauges, stable order: bucket<k>_latency_ms_p50/
            # p95/p99 + bucket<k>_batches — the dashboard's SLO series
            for k in sorted(self._bucket_lat):
                blat = sorted(self._bucket_lat[k])
                for q in (50, 95, 99):
                    names.append("bucket%d_latency_ms_p%d" % (k, q))
                    values.append(_percentile(blat, q))
                names.append("bucket%d_batches" % k)
                values.append(self._bucket_batches.get(k, 0))
        if self._cache_stats_fn:
            stats = self._cache_stats_fn()
            for k in ("compile_cache_hits", "compile_cache_misses",
                      "compiles", "disk_hits"):
                names.append(k)
                values.append(stats.get(k.replace("compile_cache_", ""),
                                        stats.get(k, 0)))
        return names, values

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))

    def bucket_latency(self, bucket: int, q: float = 99.0) -> float:
        """The bucket's recent-window latency percentile (ms) — the SLO
        probe: alert when ``bucket_latency(k, 99) > budget_ms``. NaN until
        the bucket has dispatched."""
        with self._lock:
            blat = self._bucket_lat.get(bucket)
            return _percentile(sorted(blat), q) if blat else float("nan")

    def request_size_histogram(self) -> Dict[int, int]:
        """Copy of the rows -> submit-count histogram (the BucketTuner's
        input signal)."""
        with self._lock:
            return dict(self._size_hist)

    def error_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.errors)

    def __str__(self):
        return "ServingMetrics: %s" % dict(self.get_name_value())
