"""Adaptive bucket-ladder derivation from the observed request-size mix.

The static ladder (``MXNET_SERVING_BUCKETS``) encodes a guess about the
request-size distribution; ``BucketTuner`` replaces the guess with the
measured histogram (``ServingMetrics.request_size_histogram()``). The
economics follow the XLA-compilation literature the bucket cache already
cites: programs are shape-specialized, so serving wants FEW programs
(the ``program_budget``) whose shapes sit just above the probability mass
of the size mix — every row of daylight between a request and its bucket
is padded compute the chip burns for nothing.

``derive()`` solves that placement exactly: choose at most
``program_budget`` bucket boundaries from the observed sizes (the largest
bucket pinned at ``max_batch`` so the ladder always covers every
admissible request) minimizing total padded rows, by dynamic programming
over the sorted candidate sizes — O(S^2 * K) for S distinct sizes, K
budget, evaluated off the hot path on a background engine op.

The tuner carries no lock: retunes are serialized by the server's
dedicated tuner engine variable, and ``derive`` is a pure function of its
arguments (docs/concurrency.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .batcher import ServingError


def padded_rows(ladder: Sequence[int], size_hist: Dict[int, int]) -> int:
    """Total rows dispatched (real + padding) serving ``size_hist`` on
    ``ladder``: each size pays the smallest bucket >= it. Sizes above the
    ladder are ignored (they could never have been admitted)."""
    buckets = sorted(ladder)
    total = 0
    for size, count in size_hist.items():
        for b in buckets:
            if b >= size:
                total += b * count
                break
    return total


class BucketTuner:
    """Derives the padding-optimal bucket ladder under a program budget.

    Invariants every derived ladder satisfies (property-tested):

    - ``max_batch`` is always a member, so any request the server admitted
      (rows <= max_batch) still finds a bucket after a swap — a retune can
      never strand an in-flight request;
    - at most ``program_budget`` buckets (== compiled programs per
      replica);
    - strictly increasing, all within ``[1, max_batch]``.
    """

    def __init__(self, max_batch: int, program_budget: int,
                 min_samples: int = 64, min_improvement_pct: float = 1.0):
        if max_batch < 1:
            raise ServingError("max_batch must be >= 1")
        if program_budget < 1:
            raise ServingError("program_budget must be >= 1")
        self.max_batch = int(max_batch)
        self.program_budget = int(program_budget)
        self.min_samples = int(min_samples)
        self.min_improvement_pct = float(min_improvement_pct)

    # --- pure ladder math -------------------------------------------------
    def derive(self, size_hist: Dict[int, int]) -> List[int]:
        """The optimal ladder for ``size_hist``: minimizes total padded
        rows over ladders of <= program_budget buckets that include
        ``max_batch``. An empty histogram yields ``[max_batch]``."""
        hist = {min(int(s), self.max_batch): 0 for s in size_hist if s >= 1}
        for s, c in size_hist.items():
            if s >= 1 and c > 0:
                hist[min(int(s), self.max_batch)] += int(c)
        hist = {s: c for s, c in hist.items() if c > 0}
        if not hist:
            return [self.max_batch]
        # candidate boundaries: the observed sizes plus the pinned top;
        # an optimal ladder only ever places boundaries AT observed sizes
        # (lowering a boundary to the largest size it serves never adds
        # padding), so this candidate set loses nothing.
        vals = sorted(set(hist) | {self.max_batch})
        n = len(vals)
        budget = min(self.program_budget, n)
        # seg_cost[i][j]: padding-inclusive rows for sizes in
        # (vals[i-1], vals[j]] all served by a bucket at vals[j]
        counts = [hist.get(v, 0) for v in vals]
        seg_cost = [[0] * n for _ in range(n + 1)]
        for j in range(n):
            rows = 0
            for i in range(j, -1, -1):
                rows += counts[i] * vals[j]
                seg_cost[i][j] = rows
        INF = float("inf")
        # dp[k][j]: min rows covering sizes <= vals[j] with k buckets, the
        # last at vals[j]
        dp = [[INF] * n for _ in range(budget + 1)]
        parent: List[List[Optional[Tuple[int, int]]]] = \
            [[None] * n for _ in range(budget + 1)]
        for j in range(n):
            dp[1][j] = seg_cost[0][j]
        for k in range(2, budget + 1):
            for j in range(k - 1, n):
                for i in range(k - 2, j):
                    c = dp[k - 1][i] + seg_cost[i + 1][j]
                    if c < dp[k][j]:
                        dp[k][j] = c
                        parent[k][j] = (k - 1, i)
        last = n - 1  # the ladder must end at max_batch (vals[-1])
        best_k = min(range(1, budget + 1), key=lambda k: dp[k][last])
        ladder = [vals[last]]
        k, j = best_k, last
        while parent[k][j] is not None:
            k, j = parent[k][j]
            ladder.append(vals[j])
        return sorted(ladder)

    # --- swap policy ------------------------------------------------------
    def propose(self, size_hist: Dict[int, int],
                current: Sequence[int]) -> Optional[List[int]]:
        """The ladder the server should swap to, or None to keep
        ``current``: requires ``min_samples`` observations and a relative
        padded-rows improvement of at least ``min_improvement_pct`` (the
        hysteresis that stops a noisy mix from flapping the compile
        cache)."""
        n = sum(c for s, c in size_hist.items() if 1 <= s)
        if n < self.min_samples:
            return None
        ladder = self.derive(size_hist)
        if list(ladder) == sorted(current):
            return None
        now = padded_rows(current, size_hist)
        then = padded_rows(ladder, size_hist)
        if now <= 0:
            return None
        if 100.0 * (now - then) / now < self.min_improvement_pct:
            return None
        return ladder
