"""cv2-like NDArray image API.

Capability parity with plugin/opencv (reference SURVEY §2.5): imdecode,
imencode, resize, copyMakeBorder operating on NDArrays, implemented with
host cv2 when available and numpy fallbacks otherwise (so the module
imports everywhere; only JPEG codec paths require cv2).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an image byte buffer into an (H, W, C) uint8 NDArray
    (plugin/opencv cv2.imdecode analogue)."""
    cv2 = _cv2()
    if cv2 is None:
        raise MXNetError("plugins.opencv.imdecode requires cv2")
    img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if img.ndim == 2:
        img = img[:, :, None]
    elif to_rgb:
        img = img[:, :, ::-1]
    return nd.array(np.ascontiguousarray(img))


def imencode(ext, img, params=None):
    """Encode an (H, W, C) NDArray to bytes (e.g. ext='.jpg')."""
    cv2 = _cv2()
    if cv2 is None:
        raise MXNetError("plugins.opencv.imencode requires cv2")
    arr = img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)
    ok, buf = cv2.imencode(ext, arr[:, :, ::-1] if arr.ndim == 3 else arr,
                           params or [])
    if not ok:
        raise MXNetError("imencode failed")
    return buf.tobytes()


def resize(src, size, interpolation=None):
    """Resize an (H, W, C) NDArray to size=(w, h). cv2 when present,
    nearest-neighbor numpy fallback otherwise."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    w, h = size
    cv2 = _cv2()
    if cv2 is not None:
        interp = cv2.INTER_LINEAR if interpolation is None else interpolation
        out = cv2.resize(arr, (w, h), interpolation=interp)
        if out.ndim == 2:
            out = out[:, :, None]
    else:
        ys = (np.arange(h) * arr.shape[0] / h).astype(np.int64)
        xs = (np.arange(w) * arr.shape[1] / w).astype(np.int64)
        out = arr[ys][:, xs]
    return nd.array(np.ascontiguousarray(out))


def copyMakeBorder(src, top, bot, left, right, border_type=0, value=0):
    """Pad an (H, W, C) NDArray with a constant border
    (plugin/opencv copyMakeBorder analogue)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = np.pad(arr, ((top, bot), (left, right)) + ((0, 0),) * (arr.ndim - 2),
                 mode="constant", constant_values=value)
    return nd.array(out)
