"""Run Caffe layers / losses as framework ops.

Capability parity with plugin/caffe (reference SURVEY §2.5: CaffeOp /
CaffeLoss running arbitrary ``caffe.Layer``s inside the graph, plus a
Caffe data iterator). The foreign-kernel seam is the same Custom-op
bridge the Torch plugin uses (operator.py → jax.pure_callback): the layer
executes host-side inside the jitted graph, backward via caffe's own
Backward. Everything is gated on a ``caffe`` installation (the reference
plugin is likewise opt-in via CAFFE_PATH, make/config.mk).
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from .. import operator as _operator


def _require_caffe():
    try:
        import caffe
        return caffe
    except ImportError:
        raise MXNetError(
            "mxnet_tpu.plugins.caffe requires pycaffe; the seam itself is "
            "exercised by the torch plugin (mx.torch) which shares the same "
            "Custom-op bridge")


def layer_op(prototxt_str, op_name, input_shape=(1, 1, 1, 1),
             out_shape_fn=None):
    """Register a Custom op that runs one Caffe layer defined by a
    LayerParameter prototxt string (reference plugin/caffe CaffeOp with
    its ``prototxt`` kwarg). Returns the registered op_type name.

    input_shape: the shape declared to caffe for its internal net (the
    actual runtime shape comes from each batch via blob reshape).
    out_shape_fn: optional in_shape -> out_shape hook for layers that
    change shape (conv, pooling); defaults to shape-preserving.
    """
    caffe = _require_caffe()

    class _CaffeOp(_operator.CustomOp):
        def __init__(self):
            super().__init__()
            import tempfile
            # pycaffe's Net takes a file path, and the net needs explicit
            # input dims in text format
            net_proto = (
                'input: "data"\n'
                'input_shape { %s }\n'
                'force_backward: true\n%s'   # else Net computes no diffs
                % (" ".join("dim: %d" % d for d in input_shape),
                   prototxt_str))
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".prototxt", delete=False) as f:
                f.write(net_proto)
                path = f.name
            try:
                self._net = caffe.Net(path, caffe.TEST)
            finally:
                os.unlink(path)

        def forward(self, is_train, req, in_data, out_data, aux):
            self._net.blobs["data"].reshape(*in_data[0].shape)
            self._net.blobs["data"].data[...] = in_data[0].asnumpy()
            self._net.forward()
            top = list(self._net.blobs)[-1]
            self.assign(out_data[0], req[0],
                        np.asarray(self._net.blobs[top].data))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            top = list(self._net.blobs)[-1]
            self._net.blobs[top].diff[...] = out_grad[0].asnumpy()
            self._net.backward()
            self.assign(in_grad[0], req[0],
                        np.asarray(self._net.blobs["data"].diff))

    class _CaffeOpProp(_operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def infer_shape(self, in_shape):
            out = (out_shape_fn(in_shape[0]) if out_shape_fn is not None
                   else in_shape[0])
            return in_shape, [list(out)], []

        def create_operator(self, ctx, shapes, dtypes):
            return _CaffeOp()

    _operator.register(op_name)(_CaffeOpProp)
    return op_name
