"""Run Caffe layers / losses as framework ops.

Capability parity with plugin/caffe (reference SURVEY §2.5: CaffeOp /
CaffeLoss running arbitrary ``caffe.Layer``s inside the graph, plus a
Caffe data iterator). The foreign-kernel seam is the same Custom-op
bridge the Torch plugin uses (operator.py → jax.pure_callback): the layer
executes host-side inside the jitted graph, backward via caffe's own
Backward. Everything is gated on a ``caffe`` installation (the reference
plugin is likewise opt-in via CAFFE_PATH, make/config.mk).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import operator as _operator


def _require_caffe():
    try:
        import caffe
        return caffe
    except ImportError:
        raise MXNetError(
            "mxnet_tpu.plugins.caffe requires pycaffe; the seam itself is "
            "exercised by the torch plugin (mx.torch) which shares the same "
            "Custom-op bridge")


def layer_op(prototxt_str, op_name, num_weights=0):
    """Register a Custom op that runs one Caffe layer defined by a
    LayerParameter prototxt string (reference plugin/caffe CaffeOp with
    its ``prototxt`` kwarg). Returns the registered op_type name.
    """
    caffe = _require_caffe()

    class _CaffeOp(_operator.CustomOp):
        def __init__(self):
            super().__init__()
            net_proto = ("input: \"data\"\n" + prototxt_str)
            self._net = caffe.Net(net_proto, caffe.TEST)

        def forward(self, is_train, req, in_data, out_data, aux):
            self._net.blobs["data"].reshape(*in_data[0].shape)
            self._net.blobs["data"].data[...] = in_data[0].asnumpy()
            self._net.forward()
            top = list(self._net.blobs)[-1]
            self.assign(out_data[0], req[0],
                        np.asarray(self._net.blobs[top].data))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            top = list(self._net.blobs)[-1]
            self._net.blobs[top].diff[...] = out_grad[0].asnumpy()
            self._net.backward()
            self.assign(in_grad[0], req[0],
                        np.asarray(self._net.blobs["data"].diff))

    class _CaffeOpProp(_operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"] + ["weight_%d" % i for i in range(num_weights)]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return _CaffeOp()

    _operator.register(op_name)(_CaffeOpProp)
    return op_name
