"""Optional foreign-kernel / foreign-data plugins.

Capability parity with the reference's plugin/ tree (SURVEY §2.5):

- ``plugins.opencv`` — cv2-like NDArray image API (plugin/opencv).
- ``plugins.caffe``  — run Caffe layers as ops + CaffeNet data iterator
  seam (plugin/caffe); gated on a caffe installation.
- ``plugins.sframe`` — SFrame data iterator (plugin/sframe); gated on
  turicreate/sframe.
- The Torch plugin lives at :mod:`mxnet_tpu.torch` (reference
  python/mxnet/torch.py location).

All plugins share one extension seam: the Custom-op bridge
(operator.py → jax.pure_callback) for foreign kernels, and the DataIter
contract for foreign data sources — the TPU-native equivalent of the
reference's "foreign-kernel as op" native plugins.
"""
from . import opencv

__all__ = ["opencv", "caffe", "sframe"]


def __getattr__(name):
    if name in ("caffe", "sframe"):
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
