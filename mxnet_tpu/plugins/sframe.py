"""SFrame data iterator.

Capability parity with plugin/sframe (reference SURVEY §2.5: SFrameIter
feeding SFrame/SArray columns as batches). Gated on turicreate (the
maintained SFrame distribution); with plain pandas DataFrames use
``mx.io.NDArrayIter`` directly.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from .. import ndarray as nd


class SFrameIter(DataIter):
    """Iterate an SFrame: ``data_field`` columns stacked as the input,
    optional ``label_field`` column as labels (plugin/sframe iter)."""

    def __init__(self, sframe, data_field, label_field=None, batch_size=1,
                 data_name="data", label_name="softmax_label"):
        super().__init__()
        if not (hasattr(sframe, "to_numpy") or hasattr(sframe, "select_columns")):
            raise MXNetError("SFrameIter needs an SFrame-like object "
                             "(turicreate.SFrame)")
        fields = [data_field] if isinstance(data_field, str) else list(data_field)
        cols = [np.asarray(list(sframe[f]), np.float32) for f in fields]
        self._data = np.column_stack([c.reshape(len(c), -1) for c in cols])
        self._label = (np.asarray(list(sframe[label_field]), np.float32)
                       if label_field else None)
        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self._data.shape[1:])]

    @property
    def provide_label(self):
        if self._label is None:
            return []
        return [DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor + self.batch_size > len(self._data):
            raise StopIteration
        i = self._cursor
        self._cursor += self.batch_size
        data = [nd.array(self._data[i:i + self.batch_size])]
        label = ([nd.array(self._label[i:i + self.batch_size])]
                 if self._label is not None else [])
        return DataBatch(data=data, label=label, pad=0, index=None)
