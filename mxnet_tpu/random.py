"""Global PRNG state.

The reference hands engine-tracked PRNG streams to operators through the
resource manager (src/resource.cc:21-50, ResourceRequest::kRandom). Here the
equivalent is a process-global jax PRNG key that is split per use — callers
under jit receive an explicit key instead (functional randomness).
"""
from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
_state = {"key": None, "seed": 0}


def seed(seed_state: int) -> None:
    """Seed the global generator (reference: python/mxnet/random.py seed /
    MXRandomSeed). Covers both the jax key stream (device-side sampling
    ops) and numpy's global state (host-side initializers, io shuffles),
    as the reference's seed covers all of MXNet's RNG streams."""
    import numpy as np

    with _lock:
        _state["seed"] = int(seed_state)
        _state["key"] = jax.random.PRNGKey(int(seed_state))
        np.random.seed(int(seed_state) & 0x7FFFFFFF)


def next_key():
    """Split a fresh key off the global stream."""
    with _lock:
        if _state["key"] is None:
            _state["key"] = jax.random.PRNGKey(_state["seed"])
        _state["key"], sub = jax.random.split(_state["key"])
        return sub
