"""Network visualization (reference python/mxnet/visualization.py):
print_summary table + graphviz plot_network."""
from __future__ import annotations

import json

from .symbol import Symbol
from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Layer-by-layer summary with param counts (reference
    visualization.py print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        for k, v in zip(symbol.list_arguments(), arg_shapes):
            shape_dict[k] = v
        internals = symbol.get_internals()
        for k, v in zip(internals.list_outputs(),
                        internals._infer(shape, partial=True)[1]):
            shape_dict[k] = v

    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    lines = []

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: positions[i]]
            line += " " * (positions[i] - len(line))
        lines.append(line)

    lines.append("_" * line_length)
    print_row(to_display, positions)
    lines.append("=" * line_length)
    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads_set:
                    pre_node.append(input_name)
        cur_param = 0
        if op == "null":
            if node["name"].endswith("weight") or node["name"].endswith("bias") or \
               node["name"].endswith("gamma") or node["name"].endswith("beta"):
                if show_shape and node["name"] in shape_dict:
                    cur_param = 1
                    for d in shape_dict[node["name"]]:
                        cur_param *= d
        first_connection = pre_node[0] if pre_node else ""
        fields = [
            node["name"] + " (" + op + ")",
            str(out_shape) if show_shape else "",
            cur_param,
            first_connection,
        ]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ["", "", "", pre_node[i]]
            print_row(fields, positions)
        total_params[0] += cur_param

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads_set = set(h[0] for h in conf["heads"])
    for node in nodes:
        out_shape = None
        if show_shape:
            key = node["name"] + "_output" if node["op"] != "null" else node["name"]
            if key in shape_dict:
                out_shape = shape_dict[key]
        print_layer_summary(node, out_shape)
        lines.append("_" * line_length)
    lines.append("Total params: %d" % total_params[0])
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs={}, hide_weights=True):
    """Graphviz network plot (reference visualization.py plot_network).
    Returns a graphviz.Digraph; rendering requires the graphviz binary."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    fill_colors = ["#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3",
                   "#fdb462", "#b3de69", "#fccde5"]
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and (name.endswith("_weight") or name.endswith("_bias")
                                 or name.endswith("_gamma") or name.endswith("_beta")
                                 or name.endswith("_moving_mean")
                                 or name.endswith("_moving_var")):
                continue
            attr = dict(node_attr)
            attr["fillcolor"] = fill_colors[0]
            dot.node(name=name, label=name, **attr)
        else:
            attr = dict(node_attr)
            attr["fillcolor"] = fill_colors[hash(op) % len(fill_colors)]
            dot.node(name=name, label="%s\n%s" % (op, name), **attr)
    name_set = set(n["name"] for n in nodes if not (
        n["op"] == "null" and hide_weights and (
            n["name"].endswith("_weight") or n["name"].endswith("_bias")
            or n["name"].endswith("_gamma") or n["name"].endswith("_beta")
            or n["name"].endswith("_moving_mean") or n["name"].endswith("_moving_var"))))
    for node in nodes:
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            src = nodes[item[0]]["name"]
            if src in name_set:
                dot.edge(tail_name=src, head_name=node["name"])
    return dot
