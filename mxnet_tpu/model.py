"""Model helpers + FeedForward legacy estimator.

Reimplementation of python/mxnet/model.py (SURVEY §2.4): kvstore creation
policy (_create_kvstore, model.py:40), the two update paths
(update_on_kvstore model.py:88-97 vs local updater :99-110), checkpoint
save/load (model.py:319,349), and the legacy FeedForward estimator
(model.py:387) layered on Module.
"""
from __future__ import annotations

import logging
from collections import namedtuple

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from . import telemetry
from .base import MXNetError
from .context import cpu

BatchEndParam = namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"]
)


def _create_kvstore(kvstore, num_device, arg_params):
    """Select kvstore + update placement (reference model.py:40-66)."""
    from . import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """(reference model.py:68-86)."""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """(reference model.py:88-97)."""
    with telemetry.span("model.update_params_on_kvstore",
                        domain="executor", n_params=len(param_arrays)):
        for index, pair in enumerate(zip(param_arrays, grad_arrays)):
            arg_list, grad_list = pair
            if grad_list[0] is None:
                continue
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None):
    """(reference model.py:99-122). All per-key updates are batched into one
    jitted program per device slot via Updater.update_all."""
    with telemetry.span("model.update_params", domain="executor",
                        n_params=len(param_arrays)):
        per_slot = {}
        for index, pair in enumerate(zip(param_arrays, grad_arrays)):
            arg_list, grad_list = pair
            if grad_list[0] is None:
                continue
            if kvstore:
                kvstore.push(index, grad_list, priority=-index)
                kvstore.pull(index, grad_list, priority=-index)
            for k, p in enumerate(zip(arg_list, grad_list)):
                w, g = p
                per_slot.setdefault(k, []).append(
                    (index * num_device + k, g, w))
        for pairs in per_slot.values():
            updater.update_all(pairs)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    async_write=False):
    """Save symbol JSON + params blob (reference model.py:319-347).

    The blob write is an engine op holding the file's write-var (the
    reference routes every checkpoint store through the engine). With
    ``async_write=True`` the call returns once the in-memory snapshot is
    taken — serialization and disk IO overlap continued training; readers
    (``load_checkpoint``) wait on the same var, and
    ``engine.wait_for_file(path)`` syncs explicitly."""
    from . import engine

    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    # snapshot NOW: rewrap the current (immutable) device buffers so later
    # training steps can't bleed into an in-flight async write; the span
    # covers only this host-side snapshot — the blob write is an engine op
    # that shows up as its own engine-domain event
    with telemetry.span("model.checkpoint_snapshot", domain="executor",
                        epoch=epoch, n_params=len(arg_params)):
        save_dict = {("arg:%s" % k): nd.NDArray(v._data)
                     for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): nd.NDArray(v._data)
                          for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)

    def _write():
        # atomic commit: serialize into *.params.tmp, then os.replace —
        # a crash at ANY point (including mid-serialization) leaves the
        # previously committed file intact and loadable. The fault hook
        # sits between write and rename: the worst crash point.
        import os as _os

        from .resilience import faults

        tmp = param_name + ".tmp"
        nd.save(tmp, save_dict)
        faults.maybe_raise("checkpoint_write:%s"
                           % _os.path.basename(param_name))
        _os.replace(tmp, param_name)

    engine.push_file_write(param_name, _write,
                           wait=not async_write, name="checkpoint_write")
    logging.info("Saved checkpoint to \"%s\"%s", param_name,
                 " (async)" if async_write else "")


def load_checkpoint(prefix, epoch):
    """(reference model.py:349-384). Waits on the params file's engine
    write-var first, so a checkpoint still being written asynchronously is
    read only after it is complete."""
    from . import engine

    symbol = sym_mod.load("%s-symbol.json" % prefix)
    engine.wait_for_file("%s-%04d.params" % (prefix, epoch))
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy estimator facade over Module (reference model.py:387-946)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [cpu()]
        if not isinstance(self.ctx, list):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._module = None

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params, self.aux_params)

    def _init_module(self, data, label_name="softmax_label"):
        from .module import Module

        data_names = [x[0] if isinstance(x, tuple) else x.name for x in data.provide_data]
        label_names = [x[0] if isinstance(x, tuple) else x.name for x in data.provide_label]
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data = self._prepare_data(X, y)
        self._init_module(data)
        opt_params = dict(self.kwargs)
        opt_params.setdefault("learning_rate", 0.01)
        self._module.fit(
            data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer, optimizer_params=opt_params,
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params, begin_epoch=self.begin_epoch,
            num_epoch=self.num_epoch, monitor=monitor,
        )
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._prepare_data(X)
        if self._module is None or not self._module.binded:
            self._init_module(data)
            self._module.bind(data.provide_data, data.provide_label, for_training=False)
            self._module.init_params(arg_params=self.arg_params, aux_params=self.aux_params)
        outs = self._module.predict(data, num_batch=num_batch, reset=reset)
        if isinstance(outs, list):
            return [o.asnumpy() for o in outs]
        return outs.asnumpy()

    def score(self, X, y=None, eval_metric="acc", num_batch=None, reset=True):
        data = self._prepare_data(X, y)
        if self._module is None or not self._module.binded:
            self._init_module(data)
            self._module.bind(data.provide_data, data.provide_label, for_training=False)
            self._module.init_params(arg_params=self.arg_params, aux_params=self.aux_params)
        res = self._module.score(data, eval_metric, num_batch=num_batch, reset=reset)
        return res[0][1]

    def _prepare_data(self, X, y=None):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=min(self.numpy_batch_size,
                                                np.asarray(X).shape[0]))
