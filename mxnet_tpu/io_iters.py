"""Registered data iterators: ImageRecordIter, CSVIter, MNISTIter.

Capability parity with the reference's C++ iterators (SURVEY §2.1 #27:
src/io/iter_image_recordio_2.cc, iter_csv.cc, iter_mnist.cc). The image
pipeline runs in the native C++ loader (native/recordio.cc: threaded JPEG
decode + augment + prefetch — the ImageRecordIOParser2/PrefetcherIter
redesign) with a cv2-based Python fallback; CSV/MNIST are host-side numpy
readers feeding the same DataBatch protocol.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter


class ImageRecordIter(DataIter):
    """Image .rec iterator (reference ImageRecordIter,
    iter_image_recordio_2.cc:559). Decode+augment happen on native
    threads; `prefetch_buffer` batches are in flight (PrefetcherIter
    analogue), overlapping host IO with device steps."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_width=1, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, resize=0,
                 preprocess_threads=4, num_parts=1, part_index=0,
                 seed=0, prefetch_buffer=2, round_batch=True,
                 max_rotate_angle=0, rotate=-1, fill_value=255,
                 random_h=0, random_s=0, random_l=0, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(int(x) for x in data_shape)
        self._path = path_imgrec
        self._round_batch = round_batch
        self.label_width = int(label_width)
        self._provide_data = [DataDesc("data", (batch_size,) + self.data_shape)]
        # label_width > 1: records pack k float32 labels (flag=k) and the
        # batch labels come out (N, k) — the reference's multi-label mode
        self._provide_label = [DataDesc(
            "softmax_label",
            (batch_size, self.label_width) if self.label_width > 1
            else (batch_size,))]
        self._native = None
        self._py_fallback = None
        aug_kwargs = dict(max_rotate_angle=max_rotate_angle, rotate=rotate,
                          fill_value=fill_value, random_h=random_h,
                          random_s=random_s, random_l=random_l)
        try:
            from .native import NativeImageLoader

            self._native = NativeImageLoader(
                path_imgrec, batch_size, self.data_shape,
                nthreads=preprocess_threads, rand_crop=rand_crop,
                rand_mirror=rand_mirror,
                mean_rgb=(mean_r, mean_g, mean_b),
                std_rgb=(std_r, std_g, std_b),
                part_index=part_index, num_parts=num_parts, seed=seed,
                resize_shorter=resize, queue_depth=prefetch_buffer,
                shuffle_buffer=(max(4 * batch_size, 2048) if shuffle else 0),
                label_width=self.label_width, **aug_kwargs)
        except Exception:
            self._py_fallback = _PyImageRecordReader(
                path_imgrec, self.data_shape, rand_crop, rand_mirror,
                (mean_r, mean_g, mean_b), (std_r, std_g, std_b), resize,
                part_index, num_parts, seed,
                shuffle_buffer=(max(4 * batch_size, 2048) if shuffle else 0),
                label_width=self.label_width, **aug_kwargs)

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        if self._native is not None:
            self._native.reset()
        else:
            self._py_fallback.reset()

    def next(self):
        if self._native is not None:
            out = self._native.next_batch()
            if out is None:
                raise StopIteration
            data, labels, n = out
        else:
            out = self._py_fallback.next_batch(self.batch_size)
            if out is None:
                raise StopIteration
            data, labels, n = out
        pad = self.batch_size - n
        if pad and not self._round_batch:
            # physically truncated: every remaining row is real, so pad=0
            # (consumers strip the last `pad` rows — see base_module.predict)
            data = data[:n]
            labels = labels[:n]
            pad = 0
        return DataBatch([nd.array(data.copy())], [nd.array(labels.copy())],
                         pad=pad)


class _PyImageRecordReader:
    """cv2-based fallback matching the native loader's semantics; record
    sharding + streaming shuffle delegate to _ShardedRecordStream."""

    def __init__(self, path, data_shape, rand_crop, rand_mirror, mean, std,
                 resize, part_index, num_parts, seed, shuffle_buffer=0,
                 max_rotate_angle=0, rotate=-1, fill_value=255,
                 random_h=0, random_s=0, random_l=0, label_width=1):
        self._stream = _ShardedRecordStream(path, part_index, num_parts,
                                            seed, shuffle_buffer)
        self.data_shape = data_shape
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = np.asarray(mean, np.float32).reshape(3, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(3, 1, 1)
        self.resize = resize
        self.max_rotate_angle = int(max_rotate_angle)
        self.rotate = rotate
        self.fill_value = fill_value
        self.random_h, self.random_s, self.random_l = \
            int(random_h), int(random_s), int(random_l)
        self.label_width = int(label_width)
        self._rng = np.random.RandomState(seed)

    def reset(self):
        self._stream.reset()

    def _next_my_record(self):
        return self._stream.read()

    def next_batch(self, batch_size):
        import cv2

        from . import recordio

        c, h, w = self.data_shape
        data = np.zeros((batch_size, c, h, w), np.float32)
        lw = self.label_width
        labels = np.zeros((batch_size, lw) if lw > 1 else (batch_size,),
                          np.float32)
        n = 0
        while n < batch_size:
            buf = self._next_my_record()
            if buf is None:
                break
            header, img_bytes = recordio.unpack(buf)
            img = cv2.imdecode(np.frombuffer(img_bytes, np.uint8),
                               cv2.IMREAD_COLOR)
            if img is None:
                continue
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
            if self.resize > 0:
                scale = self.resize / min(img.shape[:2])
                img = cv2.resize(img, (int(img.shape[1] * scale + 0.5),
                                       int(img.shape[0] * scale + 0.5)))
            elif img.shape[0] != h or img.shape[1] != w:
                img = cv2.resize(img, (w, h))
            if self.rotate > 0 or self.max_rotate_angle > 0:
                from .image import _rotate_arr

                angle = (self.rotate if self.rotate > 0 else
                         int(self._rng.randint(-self.max_rotate_angle,
                                               self.max_rotate_angle + 1)))
                if angle:
                    img = _rotate_arr(img, angle, self.fill_value)
            if self.random_h or self.random_s or self.random_l:
                from .image import _hsl_arr

                def draw(v):
                    return int(self._rng.randint(-v, v + 1)) if v else 0

                dh, ds, dl = (draw(self.random_h), draw(self.random_s),
                              draw(self.random_l))
                if dh or ds or dl:
                    img = _hsl_arr(img, dh, ds, dl)
            # edge-pad if the (resized) image is smaller than the crop —
            # matches the native loader's edge-clamped reads
            if img.shape[0] < h or img.shape[1] < w:
                img = np.pad(img, ((0, max(0, h - img.shape[0])),
                                   (0, max(0, w - img.shape[1])), (0, 0)),
                             mode="edge")
            y0 = (img.shape[0] - h) // 2
            x0 = (img.shape[1] - w) // 2
            if self.rand_crop and img.shape[0] > h:
                y0 = self._rng.randint(0, img.shape[0] - h + 1)
            if self.rand_crop and img.shape[1] > w:
                x0 = self._rng.randint(0, img.shape[1] - w + 1)
            img = img[y0:y0 + h, x0:x0 + w]
            if self.rand_mirror and self._rng.randint(2):
                img = img[:, ::-1]
            chw = img.transpose(2, 0, 1).astype(np.float32)
            data[n] = (chw - self.mean) / self.std
            lab = np.atleast_1d(np.asarray(header.label, np.float32))
            if lw > 1:
                k = min(lw, lab.size)
                labels[n, :k] = lab[:k]
            else:
                labels[n] = lab.flat[0]
            n += 1
        if n == 0:
            return None
        return data, labels, n


class CSVIter(DataIter):
    """CSV iterator (reference iter_csv.cc:132)."""

    def __init__(self, data_csv, data_shape, batch_size, label_csv=None,
                 label_shape=(1,), round_batch=True, **kwargs):
        super().__init__(batch_size)
        self._data = np.loadtxt(data_csv, delimiter=",", ndmin=2,
                                dtype=np.float32)
        self._data = self._data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            self._label = np.loadtxt(label_csv, delimiter=",", ndmin=2,
                                     dtype=np.float32).reshape(
                                         (-1,) + tuple(label_shape))
        else:
            self._label = np.zeros((len(self._data),) + tuple(label_shape),
                                   np.float32)
        self._round_batch = round_batch
        self._cursor = 0
        self._provide_data = [DataDesc("data",
                                       (batch_size,) + tuple(data_shape))]
        self._provide_label = [DataDesc("softmax_label",
                                        (batch_size,) + tuple(label_shape))]

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._data):
            raise StopIteration
        end = self._cursor + self.batch_size
        d = self._data[self._cursor:end]
        l = self._label[self._cursor:end]
        pad = 0
        if len(d) < self.batch_size and self._round_batch:
            # wrap around to the start, reporting the pad count
            pad = self.batch_size - len(d)
            d = np.concatenate([d, self._data[:pad]])
            l = np.concatenate([l, self._label[:pad]])
        self._cursor = end
        lab = l[:, 0] if l.ndim == 2 and l.shape[1] == 1 else l
        return DataBatch([nd.array(d)], [nd.array(lab)], pad=pad)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference iter_mnist.cc:241)."""

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, seed=0, part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        self._images = self._read_idx(image)
        self._labels = self._read_idx(label)
        if num_parts > 1:
            self._images = self._images[part_index::num_parts]
            self._labels = self._labels[part_index::num_parts]
        if flat:
            self._images = self._images.reshape(len(self._images), -1)
        else:
            self._images = self._images[:, None]  # (N, 1, 28, 28)
        self._images = self._images.astype(np.float32) / 255.0
        self._labels = self._labels.astype(np.float32)
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._order = np.arange(len(self._images))
        self._cursor = 0
        self.reset()
        shp = self._images.shape[1:]
        self._provide_data = [DataDesc("data", (batch_size,) + shp)]
        self._provide_label = [DataDesc("softmax_label", (batch_size,))]

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return np.frombuffer(f.read(), np.uint8).reshape(dims)

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            self._rng.shuffle(self._order)

    def next(self):
        if self._cursor >= len(self._images):
            raise StopIteration
        idx = self._order[self._cursor:self._cursor + self.batch_size]
        pad = 0
        if len(idx) < self.batch_size:  # pad the tail batch by wrapping
            pad = self.batch_size - len(idx)
            idx = np.concatenate([idx, self._order[:pad]])
        self._cursor += self.batch_size
        return DataBatch([nd.array(self._images[idx])],
                         [nd.array(self._labels[idx])], pad=pad)


class ImageDetRecordIter(DataIter):
    """Detection .rec iterator (reference ImageDetRecordIter,
    src/io/iter_image_recordio_2.cc:579 + image_det_aug_default.cc).

    Reads packed records through the native RecordIO reader (sharded by
    part_index/num_parts exactly like the classification iterator), decodes
    on host, applies the box-aware Det* augmenter chain (image.py:283 —
    crop/pad/resize/flip keep boxes consistent), and emits
    (data (B,C,H,W), label (B, max_objs, object_width)) with rows padded by
    ``label_pad_value`` (-1), the layout MultiBoxTarget consumes.

    Record label layout follows the reference det format: either a flat
    multiple of ``object_width``, or ``[header_width, object_width,
    ...header, objects...]`` (tools/im2rec packing).
    """

    def __init__(self, path_imgrec, data_shape, batch_size, max_objs=16,
                 object_width=5, label_pad_value=-1.0, shuffle=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0,
                 num_parts=1, part_index=0, seed=0, round_batch=True,
                 aug_list=None, label_name="label", **det_kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(int(x) for x in data_shape)
        self.max_objs = int(max_objs)
        self.object_width = int(object_width)
        self.label_pad_value = float(label_pad_value)
        self._round_batch = round_batch
        self._label_name = label_name
        self._provide_data = [DataDesc("data", (batch_size,) + self.data_shape)]
        self._provide_label = [DataDesc(
            label_name, (batch_size, self.max_objs, self.object_width))]
        self._reader = _ShardedRecordStream(
            path_imgrec, part_index, num_parts, seed,
            shuffle_buffer=(max(4 * batch_size, 2048) if shuffle else 0))
        if aug_list is None:
            import inspect

            from .image import CreateDetAugmenter

            std = (np.asarray([std_r, std_g, std_b], np.float32)
                   if (std_r != 1.0 or std_g != 1.0 or std_b != 1.0) else None)
            # std-only normalization still needs an (all-zero) mean:
            # ColorNormalizeAug is only appended when mean is present
            mean = (np.asarray([mean_r, mean_g, mean_b], np.float32)
                    if (mean_r or mean_g or mean_b or std is not None)
                    else None)
            # forward only the augmenter's own params; other kwargs
            # (preprocess_threads, prefetch_buffer, ...) are accepted and
            # ignored like the classification iterator does
            known = set(inspect.signature(CreateDetAugmenter).parameters)
            aug_kwargs = {k: v for k, v in det_kwargs.items() if k in known}
            aug_list = CreateDetAugmenter(self.data_shape, mean=mean, std=std,
                                          **aug_kwargs)
        self.det_auglist = aug_list
        self._epoch_done = False

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self._reader.reset()
        self._epoch_done = False

    def next(self):
        import cv2

        from . import recordio
        from .image import parse_det_label

        if self._epoch_done:
            raise StopIteration
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        label = np.full((self.batch_size, self.max_objs, self.object_width),
                        self.label_pad_value, np.float32)
        n = 0
        n_real = None  # real (non-wrapped) rows; set when the epoch ends mid-batch
        while n < self.batch_size:
            buf = self._reader.read()
            if buf is None:
                # round_batch (reference ImageDetRecordIter): pad the short
                # final batch with records wrapped from the epoch start, not
                # zero images. Wrap at most once per batch.
                if self._round_batch and n > 0 and n_real is None:
                    # wrapping consumes records from the next pass purely as
                    # padding: this batch ends the epoch
                    self._reader.reset()
                    self._epoch_done = True
                    n_real = n
                    continue
                break
            header, img_bytes = recordio.unpack(buf)
            img = cv2.imdecode(np.frombuffer(img_bytes, np.uint8),
                               cv2.IMREAD_COLOR)
            if img is None:
                continue
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
            boxes = parse_det_label(header.label, self.object_width)
            aimg = nd.array(img.astype(np.float32))
            for aug in self.det_auglist:
                aimg, boxes = aug(aimg, boxes)
            data[n] = aimg.asnumpy().transpose(2, 0, 1)
            k = min(len(boxes), self.max_objs)
            if k:
                # records may pack fewer columns than object_width; the
                # remainder stays at label_pad_value
                cols = min(boxes.shape[1], self.object_width)
                label[n, :k, :cols] = boxes[:k, :cols]
            n += 1
        if n == 0:
            raise StopIteration
        # pad counts non-real rows IN THE EMITTED BATCH: wrapped records
        # (round_batch=True). A physically truncated batch
        # (round_batch=False) has only real rows, so pad=0.
        if n < self.batch_size and not self._round_batch:
            data = data[:n]
            label = label[:n]
            pad = 0
        else:
            pad = self.batch_size - (n_real if n_real is not None else n)
        return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)


class _ShardedRecordStream:
    """Raw record stream: native reader (native/recordio.cc) when built,
    MXRecordIO fallback; part sharding + bounded-pool streaming shuffle
    (dmlc InputSplit + RandomSkipper analogue)."""

    def __init__(self, path, part_index, num_parts, seed, shuffle_buffer=0):
        self._native = None
        self._py = None
        self._path = path
        self._part = (part_index, num_parts)
        try:
            from .native import NativeRecordReader

            self._native = NativeRecordReader(path, part_index, num_parts)
        except Exception:
            from . import recordio

            self._py = recordio.MXRecordIO(path, "r")
        self._idx = 0
        self._rng = np.random.RandomState(seed)
        self._shuffle_buffer = shuffle_buffer
        self._pool = []

    def reset(self):
        if self._native is not None:
            self._native.reset()
        else:
            self._py.reset()
        self._idx = 0
        self._pool = []

    def _next_sequential(self):
        if self._native is not None:
            return self._native.read()
        part_index, num_parts = self._part
        while True:
            buf = self._py.read()
            if buf is None:
                return None
            mine = (self._idx % num_parts) == part_index
            self._idx += 1
            if mine:
                return buf

    def read(self):
        if self._shuffle_buffer <= 0:
            return self._next_sequential()
        while len(self._pool) < self._shuffle_buffer:
            buf = self._next_sequential()
            if buf is None:
                break
            self._pool.append(buf)
        if not self._pool:
            return None
        i = self._rng.randint(len(self._pool))
        self._pool[i], self._pool[-1] = self._pool[-1], self._pool[i]
        return self._pool.pop()


class ImageRecordUInt8Iter(ImageRecordIter):
    """Raw-pixel variant: emits uint8 batches with NO mean/std
    normalization (reference ImageRecordUInt8Iter,
    iter_image_recordio_2.cc:559 uint8 registration). The point on TPU:
    4x less host->device traffic — transfer uint8, cast/normalize
    on-device (DevicePrefetchIter(cast_dtype=...) or a leading BatchNorm
    like resnet's bn_data)."""

    def __init__(self, path_imgrec, data_shape, batch_size, **kwargs):
        for banned in ("mean_r", "mean_g", "mean_b", "std_r", "std_g",
                       "std_b"):
            kwargs.pop(banned, None)
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)

    def next(self):
        batch = super().next()
        data = [nd.NDArray(d._data.astype("uint8")) if d._data.dtype != "uint8"
                else d for d in batch.data]
        return DataBatch(data, batch.label, pad=batch.pad, index=batch.index)
