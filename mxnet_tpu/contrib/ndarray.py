"""Contrib op namespace over NDArray.

Capability parity with python/mxnet/contrib/ndarray.py: exposes the
experimental op set (CTC loss, FFT, SSD multibox, RCNN proposal,
quantization, count_sketch — reference src/operator/contrib/, SURVEY §2.1
item 19) under ``mx.contrib.nd.*``, delegating to the flat generated op
functions on :mod:`mxnet_tpu.ndarray`.
"""
from .. import ndarray as _nd

_CONTRIB_OPS = [
    "ctc_loss", "fft", "ifft", "quantize", "dequantize", "count_sketch",
    "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection", "Proposal",
]

for _name in _CONTRIB_OPS:
    if hasattr(_nd, _name):
        globals()[_name] = getattr(_nd, _name)

# Reference aliases the loss as CTCLoss in the contrib namespace.
if hasattr(_nd, "ctc_loss"):
    CTCLoss = _nd.ctc_loss

__all__ = [n for n in _CONTRIB_OPS if n in globals()] + (
    ["CTCLoss"] if "CTCLoss" in globals() else [])
