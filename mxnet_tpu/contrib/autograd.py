"""Experimental imperative autograd API.

Capability parity with python/mxnet/contrib/autograd.py (reference
:14-205): the pre-gluon experimental surface — ``set_is_training``,
``train_section``/``test_section`` scopes, ``mark_variables``,
``compute_gradient``, and the ``grad_and_loss``/``grad`` decorators —
implemented over the core tape in :mod:`mxnet_tpu.autograd`.
"""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..ndarray import NDArray


def set_is_training(is_train):
    """Set the global training-mode flag, returning the previous training
    value (reference contrib/autograd.py:14-33). Also toggles recording,
    as the reference's single flag did both."""
    prev_t = _ag.set_training(bool(is_train))
    _ag.set_recording(bool(is_train))
    return prev_t


class TrainingStateScope(object):
    """Scope manager for switching training state
    (reference contrib/autograd.py:34-53). Saves and restores the
    training and recording flags independently so nesting inside
    mx.autograd.record(train_mode=...) scopes is lossless."""

    def __init__(self, enter_state):
        self._enter_state = enter_state
        self._prev_t = None
        self._prev_r = None

    def __enter__(self):
        self._prev_t = _ag.set_training(self._enter_state)
        self._prev_r = _ag.set_recording(self._enter_state)

    def __exit__(self, ptype, value, trace):
        _ag.set_training(self._prev_t)
        _ag.set_recording(self._prev_r)


def train_section():
    """Scope for code that computes gradients
    (reference contrib/autograd.py:54-67)."""
    return TrainingStateScope(True)


def test_section():
    """Scope for inference inside a train_section
    (reference contrib/autograd.py:68-81)."""
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to variables
    (reference contrib/autograd.py:82-106)."""
    _ag.mark_variables(variables, gradients, grad_reqs)


def compute_gradient(outputs):
    """Backprop from outputs; gradients land in the buffers attached by
    :func:`mark_variables` (reference contrib/autograd.py:107-126)."""
    _ag.backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator: return a function computing both gradient of ``func``'s
    output w.r.t. its arguments and the output itself
    (reference contrib/autograd.py:127-158)."""

    @functools.wraps(func)
    def wrapped(*args):
        assert all(isinstance(x, NDArray) for x in args), (
            "type of autograd input should be NDArray.")
        if argnum is not None:
            argnums = argnum if isinstance(argnum, (list, tuple)) else [argnum]
        else:
            argnums = list(range(len(args)))
        variables = [args[i] for i in argnums]
        from .. import ndarray as _nd
        grads = [_nd.zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        compute_gradient([outputs] if isinstance(outputs, NDArray)
                         else list(outputs))
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """Decorator: return a function computing only the gradient
    (reference contrib/autograd.py:159-205)."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]

    return wrapped
