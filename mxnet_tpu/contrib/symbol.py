"""Contrib op namespace over Symbol.

Capability parity with python/mxnet/contrib/symbol.py: the same
experimental op set as :mod:`mxnet_tpu.contrib.ndarray` but building
symbolic graph nodes, delegating to the generated op functions on
:mod:`mxnet_tpu.symbol`.
"""
from .. import symbol as _sym

_CONTRIB_OPS = [
    "ctc_loss", "fft", "ifft", "quantize", "dequantize", "count_sketch",
    "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection", "Proposal",
]

for _name in _CONTRIB_OPS:
    if hasattr(_sym, _name):
        globals()[_name] = getattr(_sym, _name)

if hasattr(_sym, "ctc_loss"):
    CTCLoss = _sym.ctc_loss

__all__ = [n for n in _CONTRIB_OPS if n in globals()] + (
    ["CTCLoss"] if "CTCLoss" in globals() else [])
