"""TensorBoard metric-logging callback.

Capability parity with python/mxnet/contrib/tensorboard.py (reference
:8-56): a batch-end callback that writes eval-metric scalars to an event
log. Writer backends are optional; we try ``torch.utils.tensorboard``
(baked into this image) and degrade to an in-memory record so the
callback stays usable without any writer installed.
"""
from __future__ import annotations


class LogMetricsCallback(object):
    """Log metrics periodically in TensorBoard
    (reference contrib/tensorboard.py:8-56).

    Usage: ``mod.fit(..., batch_end_callback=LogMetricsCallback(dir))``.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.history = []  # (name, value) record kept even without a writer
        self._step = 0
        try:
            from torch.utils.tensorboard import SummaryWriter
            self.summary_writer = SummaryWriter(logging_dir)
        except Exception:
            self.summary_writer = None

    def __call__(self, param):
        """Batch-end callback: dump the metric's name/value pairs."""
        if param.eval_metric is None:
            return
        name_value = param.eval_metric.get_name_value()
        self._step += 1
        for name, value in name_value:
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.history.append((name, value))
            if self.summary_writer is not None:
                self.summary_writer.add_scalar(name, value, self._step)
