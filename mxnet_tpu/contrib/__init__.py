"""Experimental contrib namespace.

Capability parity with python/mxnet/contrib/ (reference): ``autograd``
(experimental imperative-gradient API), ``ndarray``/``symbol`` (contrib op
namespaces — CTC, fft, multibox, proposal, quantization), ``tensorboard``
(metric-logging callback, gated on an available writer).
"""
from . import autograd
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from . import tensorboard
