"""Data iterators.

TPU-native analogue of python/mxnet/io.py + the C++ iterator pipeline
(src/io/, SURVEY §2.1 #27). This module provides the Python-visible layer:
DataDesc/DataBatch/DataIter contracts, NDArrayIter, ResizeIter, and
PrefetchingIter (background-thread double buffering ≡ the reference's
PrefetcherIter, iter_prefetcher.h). File-format iterators (MNISTIter,
CSVIter, ImageRecordIter) live in iterators.py / image.py and register here.
"""
from __future__ import annotations

import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape(+dtype+layout) of one input (reference io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator contract (reference io.py DataIter / IIterator<DataBatch>)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex(),
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


def _init_data(data, allow_empty, default_name):
    """Normalize input data into list of (name, numpy) (reference io.py)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    return [
        (k, v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
        for k, v in data.items()
    ]


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with pad/discard/roll_over last-batch
    handling (reference io.py:470)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]

        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n

        self.data_list = [v for _, v in self.data] + [v for _, v in self.label]
        self.num_source = len(self.data_list)
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=None,
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor : self.cursor + self.batch_size]) for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [
            nd.array(np.concatenate([v[self.cursor :], v[:pad]], axis=0))
            for _, v in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators — the Python
    face of the reference's PrefetcherIter double buffering
    (iter_prefetcher.h:28,129 / io.py PrefetchingIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)
        ]
        for thread in self.prefetch_threads:
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc)
                 else DataDesc(*x) for x in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc)
                 else DataDesc(*x) for x in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Number of entry mismatches between iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
        )
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DevicePrefetchIter(DataIter):
    """Host→device prefetch: engine ops pull batches from the wrapped
    iterator and *place them on device* ahead of consumption, so host
    decode AND the H2D transfer overlap the device step — the TPU-native
    recreation of the reference's pinned-buffer + copy-stream pipelining
    (PrefetcherIter feeding kCopyToGPU engine ops, SURVEY §3.1, and the
    infeed double-buffering called out in §7's risk register).

    Each prefetch stage is an engine op holding the iterator's write-var
    (exactly the reference: iter_prefetcher.h:28 pushes the copy as an
    engine op on the output's var), so base-iterator access serializes in
    push order while independent host work (checkpoint writes, PS RPCs)
    runs concurrently on the same worker pool.

    depth = number of device-resident batches kept in flight (2 =
    classic double buffering)."""

    def __init__(self, base, ctx=None, depth=2, cast_dtype=None):
        import queue as _queue

        from . import engine

        super().__init__(getattr(base, "batch_size", 0))
        self._base = base
        self._ctx = ctx
        self._cast = cast_dtype  # cast data ON DEVICE after the transfer
        #   (uint8 wire format + device-side cast: 4x less H2D traffic)
        self._depth = max(1, int(depth))
        self._q = _queue.Queue()
        self._gen = 0
        self._lock = threading.Lock()
        self._engine = engine
        self._iter_var = engine.get().new_variable()
        self._closed = False
        self._done = False
        self._wedged = False  # a prefetch op failed to finish in time
        self._waiter = None   # reusable bounded-wait thread
        self._waiter_covers = 0  # ops_pushed snapshot when waiter started
        self._ops_pushed = 0
        self._start()

    def _device(self):
        import jax

        if self._ctx is not None:
            return self._ctx.jax_device()
        return jax.devices()[0]

    def _place(self, batch):
        import jax
        from . import ndarray as _ndmod

        dev = self._device()

        def put(arr, cast=None):
            data = arr._data if isinstance(arr, _ndmod.NDArray) else arr
            out = jax.device_put(data, dev)
            if cast is not None and str(out.dtype) != str(cast):
                out = out.astype(cast)  # on-device cast, off the wire
            # NO per-batch block_until_ready: transfers pipeline
            # asynchronously (a blocking sync would cost a full dispatch
            # round trip per batch on remote/tunneled devices); the queue
            # depth bounds batches in flight.
            return _ndmod.NDArray(out)

        return DataBatch([put(d, self._cast) for d in batch.data],
                         [put(l) for l in batch.label] if batch.label else [],
                         pad=batch.pad, index=batch.index)

    def _start(self):
        with self._lock:
            self._gen += 1
        self._q = type(self._q)()
        self._done = False
        # prime the pipeline: `depth` prefetch ops in flight; next() pushes
        # one replacement op per consumed batch
        for _ in range(self._depth):
            self._push_fetch()

    def _push_fetch(self):
        with self._lock:
            gen = self._gen
        q = self._q

        def fetch(gen=gen, q=q):
            with self._lock:
                if gen != self._gen:  # retired generation: no-op
                    return
            try:
                batch = self._base.next()
            except StopIteration:
                q.put(None)
                return
            except BaseException as e:  # surface in the consumer —
                q.put(e)                # a silent death would hang next()
                return
            try:
                q.put(self._place(batch))
            except BaseException as e:
                q.put(e)

        self._ops_pushed += 1
        self._engine.get().push(fetch, mutable_vars=[self._iter_var],
                                name="prefetch_batch")

    @property
    def provide_data(self):
        return self._base.provide_data

    @property
    def provide_label(self):
        return self._base.provide_label

    def _retire_worker(self):
        """Invalidate queued prefetch ops and WAIT on the iterator var so
        nothing touches the (non-thread-safe) base iterator afterwards."""
        with self._lock:
            self._gen += 1  # in-queue ops become no-ops
        # Bounded wait: a fetch wedged in a device transfer must not hang
        # reset()/close() (and interpreter shutdown) forever. A waiter
        # thread only proves quiescence for ops pushed BEFORE it started
        # (the native WaitForVar read op is enqueued at call time), so it
        # is reusable only while no new fetch has been pushed since; then
        # a wedged retry can re-check briefly instead of a full 60s.
        waiter = self._waiter
        reusable = (waiter is not None and waiter.is_alive()
                    and self._waiter_covers == self._ops_pushed)
        if not reusable:
            waiter = threading.Thread(
                target=self._engine.get().wait_for_var,
                args=(self._iter_var,), daemon=True)
            self._waiter_covers = self._ops_pushed
            waiter.start()
            self._waiter = waiter
        timeout = 5 if (self._wedged and reusable) else 60
        waiter.join(timeout=timeout)
        if waiter.is_alive():
            self._wedged = True
            raise RuntimeError(
                "DevicePrefetchIter: in-flight prefetch op did not finish "
                "within %ds; refusing to reuse the base iterator while it "
                "may still be reading it" % timeout)
        self._wedged = False
        self._waiter = None
        # drop already-produced batches of the retired generation
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass

    def reset(self):
        if self._closed:
            raise RuntimeError("DevicePrefetchIter is closed (its engine "
                               "variable was retired); construct a new one")
        self._retire_worker()
        self._base.reset()
        self._start()

    def next(self):
        if self._closed:
            raise RuntimeError("DevicePrefetchIter is closed (its engine "
                               "variable was retired); construct a new one")
        if self._done:
            raise StopIteration  # exhausted: the None sentinel is one-shot
        batch = self._q.get()
        if batch is None:
            self._done = True
            raise StopIteration
        if isinstance(batch, BaseException):
            self._done = True
            raise batch
        # keep `depth` fetches in flight
        self._push_fetch()
        return batch

    def close(self):
        """Retire in-flight prefetch ops — call before interpreter
        shutdown: an engine op killed mid-device-transfer aborts the
        process on some PJRT plugins. Also retires the engine variable:
        long-running jobs construct many iterators, and an undeleted var
        per instance grows the engine's var table without bound."""
        if getattr(self, "_closed", False):
            return
        self._retire_worker()
        self._engine.get().delete_variable(self._iter_var)
        self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# Registered iterators (reference MXNET_REGISTER_IO_ITER classes) live in
# io_iters.py; re-exported here so callers use mx.io.ImageRecordIter etc.
from .io_iters import (ImageRecordIter, ImageRecordUInt8Iter,  # noqa: E402,F401
                       ImageDetRecordIter, CSVIter, MNISTIter)
