"""KVStore — the communication plane.

TPU-native redesign of src/kvstore/ (SURVEY §2.1 #22-26, §5.8). The
*interface* is the reference's: Init/Push/Pull over integer-or-string keys,
set_updater/set_optimizer, rank/num_workers/barrier, type factory
(`create('local'|'device'|'dist_sync'|'dist_device_sync'|'dist_async')`).

The *mechanism* is not a parameter server: on TPU, gradients produced by a
mesh-sharded executor are already all-reduced in-graph by XLA (ICI
collectives inserted from sharding propagation — the CommDevice P2P reduce,
comm.h:211-373, has no hand-written counterpart). What remains for the
KVStore object is:

- `local`/`device`: aggregate per-device gradient NDArrays (tree-sum on
  device) and run the updater on the merged copy — matching
  KVStoreLocal::Push/Pull (kvstore_local.h:50-88). With one sharded executor
  the per-key list has a single, already-reduced entry.
- `dist_sync`/`dist_device_sync`: the same code over a multi-host runtime
  (jax.distributed): every host holds replicated weights, gradient arrays
  are global jax.Arrays whose reduction rode ICI/DCN inside the step;
  the updater is applied identically on every host (deterministic), which
  IS the sync parameter-server semantics (kvstore_dist_server.h:164-198)
  without the server round-trip.
- `dist_async`: per-host immediate updates (Hogwild semantics,
  kvstore_dist_server.h:199-207) — each host updates its own weight copy
  without a barrier; drift is reconciled on explicit `pull` via mean.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from . import ndarray as nd
from . import telemetry
from .telemetry import context as _trace_context
from .base import MXNetError
from .ndarray import NDArray

# traffic counters (default-on; MXNET_TELEMETRY=0 makes inc() a no-op),
# created once at import so the hot path is a single bound-method call —
# the registry surfaces them in exposition()/get_name_value()
_push_total = telemetry.registry.counter(
    "kvstore_push_total", help="kvstore push calls (keys)")
_push_bytes = telemetry.registry.counter(
    "kvstore_push_bytes_total", help="gradient bytes pushed")
_pull_total = telemetry.registry.counter(
    "kvstore_pull_total", help="kvstore pull calls (keys)")
_pull_bytes = telemetry.registry.counter(
    "kvstore_pull_bytes_total", help="weight bytes pulled")
_barrier_total = telemetry.registry.counter(
    "kvstore_barrier_total", help="kvstore barrier calls")


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None
        self._is_dist = "dist" in kv_type

    # --- identity (reference kvstore.h:223-286) ---------------------------
    @property
    def rank(self) -> int:
        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self._is_dist else 1

    def barrier(self):
        """Global barrier (reference Barrier → ps::Postoffice::Barrier).
        On jax runtime: a tiny all-reduce forces synchronization."""
        _barrier_total.inc()
        if self._is_dist and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            with telemetry.span("kvstore.barrier", domain="kvstore"):
                multihost_utils.sync_global_devices("kvstore_barrier")

    # --- data plane -------------------------------------------------------
    def init(self, key, value):
        """Initialize key(s) (reference KVStore::Init, kvstore.h:64)."""
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("duplicate init of key %r" % (k,))
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Reduce value(s) into the store; run updater if set
        (reference KVStoreLocal::Push, kvstore_local.h:50-73).

        value may be one NDArray or a list (one per device) per key."""
        keys, grouped = _group_kv(key, value)
        nbytes = 0
        with telemetry.span("kvstore.push", domain="kvstore",
                            n_keys=len(keys)):
            for k, vals in zip(keys, grouped):
                merged = _reduce(vals)
                nbytes += merged._data.nbytes
                if self._updater is not None:
                    if k not in self._store:
                        raise MXNetError(
                            "push to uninitialized key %r" % (k,))
                    stored = self._store[k]
                    ssh = stored._data.sharding
                    gsh = merged._data.sharding
                    if ssh != gsh:
                        if (ssh.device_set == gsh.device_set
                                and not ssh.is_fully_replicated):
                            # the stored master value is deliberately sharded
                            # over the same mesh (ZeRO-1 weight-update
                            # layout): bring the merged gradient TO the
                            # shards (the resharding device_put IS the
                            # reduce_scatter leg) instead of destroying the
                            # stored layout
                            merged = NDArray(jax.device_put(merged._data,
                                                            ssh))
                        else:
                            # adopt the gradient's (mesh) sharding so the
                            # fused update runs where the executor's arrays
                            # live — the analogue of the reference's
                            # merge-buffer placement (comm.h:333-361)
                            stored._data = jax.device_put(stored._data, gsh)
                    self._updater(_updater_key(k), merged, stored)
                else:
                    prev = self._store.get(k)
                    if prev is not None:
                        ssh = prev._data.sharding
                        gsh = merged._data.sharding
                        if (ssh != gsh
                                and ssh.device_set == gsh.device_set
                                and not ssh.is_fully_replicated):
                            # no-updater aggregation must not densify a
                            # deliberately sharded stored value (ZeRO
                            # weight layout): reshard the merged result TO
                            # the stored layout before replacing it —
                            # mirrors the updater branch above
                            merged = NDArray(jax.device_put(merged._data,
                                                            ssh))
                    self._store[k] = merged
        _push_total.inc(len(keys))
        _push_bytes.inc(nbytes)

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value into out array(s) (reference
        KVStoreLocal::Pull → Comm::Broadcast, kvstore_local.h:75-88)."""
        keys, grouped = _group_kv(key, out)
        nbytes = 0
        with telemetry.span("kvstore.pull", domain="kvstore",
                            n_keys=len(keys)):
            for k, outs in zip(keys, grouped):
                if k not in self._store:
                    raise MXNetError("pull of uninitialized key %r" % (k,))
                src = self._store[k]
                for o in outs:
                    # broadcast into the target's own sharding (replicated
                    # over the mesh for params) — Comm::Broadcast
                    # (comm.h:268). When the stored value is ZeRO-1 sharded
                    # (dist_sync with the sharded update) this device_put is
                    # the weight all-gather: the puller always receives full
                    # values, never a bare shard
                    if o._data.sharding != src._data.sharding:
                        o._data = jax.device_put(src._data, o._data.sharding)
                    else:
                        o._data = src._data
                    nbytes += o._data.nbytes
        _pull_total.inc(len(keys))
        _pull_bytes.inc(nbytes)

    # --- updater / optimizer ---------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Install an optimizer (reference kvstore.py set_optimizer: pickles
        the optimizer to servers in dist mode; here every host constructs the
        same updater and applies it deterministically)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # --- liveness (reference kvstore_dist.h:159-168) ----------------------
    def num_dead_node(self, node_id=0, timeout_sec=60):
        """Dead-node query. jax.distributed's coordinator enforces liveness
        (failed hosts abort the job), so a live process observes 0."""
        return 0

    def send_command_to_servers(self, head, body):
        pass  # no server processes in the collective design

    def __del__(self):
        pass


def _updater_key(k):
    return int(k) if isinstance(k, (int, np.integer)) or (isinstance(k, str) and k.isdigit()) else k


def _key_value(key, value):
    if isinstance(key, (list, tuple)):
        if isinstance(value, (list, tuple)) and len(key) == len(value):
            return list(key), list(value)
        raise MXNetError("key/value length mismatch")
    return [key], [value]


def _group_kv(key, value):
    """Group duplicate keys (reference GroupKVPairs, kvstore_local.h:95-120)."""
    if not isinstance(key, (list, tuple)):
        key = [key]
        value = [value]
    keys: List[Any] = []
    grouped: List[List[NDArray]] = []
    pos: Dict[Any, int] = {}
    for k, v in zip(key, value):
        vals = v if isinstance(v, (list, tuple)) else [v]
        if k in pos:
            grouped[pos[k]].extend(vals)
        else:
            pos[k] = len(keys)
            keys.append(k)
            grouped.append(list(vals))
    return keys, grouped


def _reduce(vals: List[NDArray]) -> NDArray:
    """Tree-sum on device — the CommDevice::Reduce analogue (comm.h:223).
    For a single (possibly mesh-sharded) array this is a no-copy pass-through
    because XLA already reduced it in-graph."""
    if len(vals) == 1:
        return NDArray(vals[0]._data)
    acc = vals[0]._data
    for v in vals[1:]:
        acc = acc + v._data
    return NDArray(acc)


class PSKVStore(KVStore):
    """Parameter-server-backed dist store (kvstore_server.py): weights live
    on the server; push/pull are RPCs — the reference KVStoreDist worker
    (kvstore_dist.h). Selected when a PS URI is configured; the collective
    (in-graph all-reduce) KVStore remains the default dist path."""

    def __init__(self, kv_type):
        super().__init__(kv_type)
        from . import engine
        from .kvstore_server import PSClient, num_workers

        self._n_workers = num_workers()
        self._rank = int(os.environ.get(
            "MXNET_TPU_WORKER_RANK", os.environ.get("DMLC_WORKER_ID", "0")))
        # rank-tagged client: sync merges dedupe per sender (recovery)
        self._client = PSClient(rank=self._rank)
        # PS RPCs are engine ops with one var per key (the reference's
        # KVStoreDist: ZPush/ZPull run on the engine holding the buffer
        # vars, kvstore_dist.h:233-241) — pushes return immediately and
        # overlap the training step; a pull of the same key orders after
        # every outstanding push of that key.
        self._engine = engine
        self._key_vars = {}
        self._rpc_errs = []
        self._errs_lock = threading.Lock()
        # liveness registration (ps-lite heartbeat analogue): hello on the
        # control channel tells the server this rank is up; the reply says
        # whether this is a RECOVERY (the rank was registered before and
        # its connection dropped — reference kvstore_dist.h:39-42). A
        # recovering worker skips the startup barrier (peers are mid-run
        # and will not join it) and pulls current weights — the server's
        # copy is authoritative.
        self._recovery = (self._client.hello(self._rank) == "recovery"
                          or bool(os.environ.get("MXNET_TPU_IS_RECOVERY")))
        self._hb_stop = threading.Event()
        hb = float(os.environ.get("MXNET_TPU_PS_HEARTBEAT", "2"))

        def _heartbeat_loop():
            while not self._hb_stop.wait(hb):
                try:
                    self._client.heartbeat(self._rank)
                except Exception:
                    return  # server gone; workers fail at the next RPC
        if hb > 0:
            threading.Thread(target=_heartbeat_loop, daemon=True).start()
        if self._rank == 0 and not self._recovery:
            # rank-0 worker announces the consistency mode, as in
            # kvstore.cc:31-38 (kSyncMode command to servers)
            self._client.set_sync("async" not in kv_type)

    def _key_var(self, key):
        v = self._key_vars.get(key)
        if v is None:
            v = self._engine.get().new_variable()
            self._key_vars[key] = v
        return v

    def _record_err(self, e):
        with self._errs_lock:
            self._rpc_errs.append(e)

    def _raise_pending(self):
        with self._errs_lock:
            errs, self._rpc_errs = self._rpc_errs, []
        if errs:
            raise errs[0]

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._n_workers

    def init(self, key, value):
        keys, values = _key_value(key, value)
        ctx = _trace_context.current_context()
        for k, v in zip(keys, values):
            arr = v.asnumpy()
            self._engine.get().push(
                lambda k=k, arr=arr, c=ctx: self._safe_rpc(
                    lambda: self._client.init(k, arr), c),
                mutable_vars=[self._key_var(k)], name="ps_init")
        self.barrier()

    def _safe_rpc(self, fn, ctx=None):
        """Run an RPC thunk on the engine worker thread; when the
        submitting thread carried a trace context the caller passes it
        here, so the PSClient serializes it as a traceparent header on
        the wire even though the RPC runs threads away."""
        try:
            if ctx is not None:
                with _trace_context.use(ctx):
                    fn()
            else:
                fn()
        except BaseException as e:  # surface at the next sync point
            self._record_err(e)

    def push(self, key, value, priority=0):
        """Async: the RPC (device readback + wire) runs as an engine op
        holding the key's var — the training thread keeps going, exactly
        the reference's engine-threaded ZPush (kvstore_dist.h:233-241)."""
        import jax.numpy as jnp

        keys, grouped = _group_kv(key, value)
        nbytes = 0
        ctx = _trace_context.current_context()
        with telemetry.span("kvstore.push", domain="kvstore",
                            n_keys=len(keys), ps=True,
                            **(ctx.stamps() if ctx is not None else {})):
            for k, vals in zip(keys, grouped):
                merged = _reduce(vals)  # local device reduce before the wire
                nbytes += merged._data.nbytes
                # device-side copy: the caller's buffer may be DONATED by
                # the next fused step before the engine op reads it back;
                # the copy is a fresh buffer, and the (slow, tunneled) D2H
                # readback still overlaps training inside the engine op
                m = NDArray(jnp.copy(merged._data))
                self._engine.get().push(
                    lambda k=k, m=m, c=ctx: self._safe_rpc(
                        lambda: self._client.push(k, m.asnumpy()), c),
                    mutable_vars=[self._key_var(k)], priority=priority,
                    name="ps_push")
        _push_total.inc(len(keys))
        _push_bytes.inc(nbytes)

    def pull(self, key, out=None, priority=0):
        keys, grouped = _group_kv(key, out)
        ctx = _trace_context.current_context()
        with telemetry.span("kvstore.pull", domain="kvstore",
                            n_keys=len(keys), ps=True,
                            **(ctx.stamps() if ctx is not None else {})):
            self._pull_impl(keys, grouped, priority)
        _pull_total.inc(len(keys))
        _pull_bytes.inc(sum(o._data.nbytes
                            for outs in grouped for o in outs))

    def _pull_impl(self, keys, grouped, priority):
        ctx = _trace_context.current_context()
        for k, outs in zip(keys, grouped):
            ref_shape = tuple(outs[0].shape)

            def do_pull(k=k, outs=outs, ref_shape=ref_shape):
                # element count selects the same shard plan as the push
                # side (kvstore_dist.h EncodeKey); sharded pulls are flat
                val = self._client.pull(k, size=int(np.prod(ref_shape)))
                val = np.asarray(val).reshape(ref_shape)
                for o in outs:
                    # preserve the target's mesh sharding (Comm::Broadcast
                    # semantics), as base KVStore.pull does
                    o._data = jax.device_put(val.astype(o.dtype),
                                             o._data.sharding)

            # engine-ordered after every outstanding push of this key
            self._engine.get().push(
                lambda f=do_pull, c=ctx: self._safe_rpc(f, c),
                mutable_vars=[self._key_var(k)],
                priority=priority, name="ps_pull")
        # one pushed barrier over every pulled key: unlike a per-key
        # wait_for_var loop it is a single engine op and orders after the
        # RPCs' host-side completion as well
        self._engine.fence([self._key_var(k) for k in keys],
                           name="ps_pull_fence").wait()
        self._raise_pending()
        # a completed pull means this worker holds current server weights:
        # recovery is over, future barriers are real again
        self._recovery = False

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        if self._rank == 0 and not self._recovery:
            self._client.set_optimizer(optimizer)
        self.barrier()

    def num_dead_node(self, node_id=0, timeout_sec=60):
        """Real liveness count from the server's heartbeat registry
        (reference kvstore_dist.h:159-168 GetDeadNodes): workers whose
        control connection dropped or whose heartbeat is older than
        timeout_sec. Rides the dedicated control channel, so it works
        while this worker's data connections are blocked in a sync-mode
        merge — exactly when survivors need to ask."""
        return len(self._client.dead_nodes(timeout_sec))

    def barrier(self):
        _barrier_total.inc()
        with telemetry.span("kvstore.barrier", domain="kvstore", ps=True):
            # flush every queued push/pull first: a barrier with RPCs still
            # in the engine queue would not be a barrier
            self._engine.fence(list(self._key_vars.values()),
                               name="ps_barrier_fence").wait()
        self._raise_pending()
        if self._recovery:
            # startup barrier skip (reference is_recovery,
            # kvstore_dist.h:77-79): the peers' startup barrier completed
            # long ago; joining a fresh one would hang this worker AND
            # poison the count for the peers' next real barrier
            return
        self._client.barrier()

    def finish_recovery(self):
        """Called (or implied by the first completed pull) once a
        recovering worker has the current weights: rejoin normal barrier
        semantics."""
        self._recovery = False

    def stop_server(self):
        self._engine.fence(list(self._key_vars.values()),
                           name="ps_stop_fence").wait()
        self._raise_pending()
        self._hb_stop.set()
        if self._rank == 0:
            self._client.stop()


def create(name="local") -> KVStore:
    """Factory (reference KVStore::Create, src/kvstore/kvstore.cc:17-45).
    dist types use the in-graph collective store unless a parameter server
    is configured (MXNET_TPU_PS_URI / DMLC_PS_ROOT_URI), in which case the
    PS worker client is returned — the reference's `dist_*` topology."""
    if not isinstance(name, str):
        raise TypeError("name must be string")
    valid = (
        "local", "device", "local_allreduce_cpu", "local_allreduce_device",
        "dist_sync", "dist_device_sync", "dist_async", "dist_sync_device",
    )
    if name not in valid:
        raise MXNetError("unknown kvstore type %r (valid: %s)" % (name, valid))
    if "dist" in name:
        import os

        if os.environ.get("MXNET_TPU_PS_URI") or os.environ.get(
                "DMLC_PS_ROOT_URI"):
            return PSKVStore(name)
    return KVStore(name)
