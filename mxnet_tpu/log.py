"""Logging with colored level labels.

Capability parity with python/mxnet/log.py (reference :19-127): a custom
``logging.Formatter`` that prints ``date level message`` with ANSI-colored
level labels on ttys, and a ``get_logger`` helper wiring it to stream or
file handlers.
"""
from __future__ import annotations

import logging
import sys

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

PY3 = sys.version_info[0] >= 3


class _Formatter(logging.Formatter):
    """Formatter: colored single-letter level label + time + message
    (reference log.py:19-61)."""

    _COLORS = {
        logging.WARNING: "\x1b[33m",   # yellow
        logging.ERROR: "\x1b[31m",     # red
        logging.CRITICAL: "\x1b[35m",  # magenta
    }
    _LABELS = {
        logging.CRITICAL: "C",
        logging.ERROR: "E",
        logging.WARNING: "W",
        logging.INFO: "I",
        logging.DEBUG: "D",
    }

    def __init__(self):
        datefmt = "%m%d %H:%M:%S"
        super().__init__(datefmt=datefmt)

    def _get_color(self, level):
        return self._COLORS.get(level, "\x1b[32m")  # default green

    def _get_label(self, level):
        return self._LABELS.get(level, "U")

    def format(self, record):
        fmt = ""
        if sys.stderr.isatty():
            fmt += self._get_color(record.levelno)
        fmt += self._get_label(record.levelno)
        fmt += "%(asctime)s %(process)d %(pathname)s:%(lineno)d"
        if sys.stderr.isatty():
            fmt += "\x1b[0m"
        fmt += " %(message)s"
        self._style._fmt = fmt
        return super().format(record)


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias of :func:`get_logger` (reference log.py:62-71)."""
    import warnings
    warnings.warn("getLogger is deprecated, use get_logger instead",
                  DeprecationWarning)
    return get_logger(name, filename, filemode, level)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Get a logger configured with the mxnet formatter
    (reference log.py:72-127). Handlers are attached only once per name."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
        else:
            hdlr = logging.StreamHandler()
        hdlr.setFormatter(_Formatter())
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger
