"""Parameter-server service: the ps-lite functional equivalent.

Parity with src/kvstore/kvstore_dist_server.h + python/mxnet/
kvstore_server.py (SURVEY §2.1 #25-26, §3.4). The collective (`dist_sync`)
path of this framework needs no servers — gradients all-reduce in-graph
over ICI/DCN (parallel/). This module exists for the OTHER capability the
reference's PS provides: **asynchronous** (Hogwild) and hierarchical
updates where an optimizer step runs on merged gradients *outside* the
training step, plus the worker/server/scheduler process topology that
`tools/launch.py` spawns.

Design (host-side, CPU — weights live on servers, as in the reference):

- Transport: `multiprocessing.connection` (stdlib, pickle framing) instead
  of ZeroMQ. One `Listener` per server; each worker holds one duplex
  connection. `SArray` zero-copy becomes numpy buffers.
- Server loop: connection-handler threads enqueue requests onto a single
  dispatch queue consumed by ONE thread — the reference's single-thread
  `Executor` run loop (kvstore_dist_server.h:28-85), which serializes all
  state mutation (no locks on the store itself).
- Sync mode: pushes accumulate into a per-key merge buffer; the updater
  runs once when all `num_workers` contributions arrived, then every
  waiting worker gets its reply — exactly DataHandle sync
  (kvstore_dist_server.h:164-198). Async mode applies immediately
  (:199-207).
- `set_optimizer` ships a pickled optimizer to the server, as the
  reference pickles through `_send_command_to_servers`
  (python/mxnet/kvstore.py set_optimizer).

Role selection mirrors the reference's import-time dispatch
(python/mxnet/kvstore_server.py:26-67): a process with
MXNET_TPU_ROLE/DMLC_ROLE == "server" calls `run()` and blocks until a
worker sends stop.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import queue
from typing import Any, Dict, Optional

import numpy as np
from multiprocessing.connection import Client, Listener

from .base import MXNetError

_AUTH = b"mxnet_tpu_ps"


def _uri():
    uri = os.environ.get("MXNET_TPU_PS_URI") or os.environ.get(
        "DMLC_PS_ROOT_URI")
    if uri is None:
        return None
    if ":" in uri:
        host, port = uri.rsplit(":", 1)
    else:
        host, port = uri, os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    return (host, int(port))


def role() -> str:
    return os.environ.get("MXNET_TPU_ROLE",
                          os.environ.get("DMLC_ROLE", "worker"))


def num_workers() -> int:
    return int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                              os.environ.get("DMLC_NUM_WORKER", "1")))


class KVStoreServer:
    """One server process's state + run loop."""

    def __init__(self, address=None, n_workers: Optional[int] = None,
                 sync_mode: bool = True):
        self.address = address or _uri() or ("127.0.0.1", 9091)
        self.n_workers = n_workers or num_workers()
        self.sync_mode = sync_mode
        self.store: Dict[Any, np.ndarray] = {}
        self.updater = None
        self._merge: Dict[Any, np.ndarray] = {}
        self._merge_count: Dict[Any, int] = {}
        self._waiting: Dict[Any, list] = {}
        self._barrier_conns: list = []
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._ready = threading.Event()

    # --- request handling (single dispatch thread) ------------------------
    def _apply(self, key, merged):
        if self.updater is not None:
            if key not in self.store:
                self.store[key] = np.zeros_like(merged)
            self.updater(key, merged, self.store[key])
        else:
            self.store[key] = np.array(merged, copy=True)

    def _handle(self, conn, req):
        op = req[0]
        if op == "init":
            key, val = req[1], req[2]
            if key not in self.store:  # first init wins (rank-0 semantics)
                self.store[key] = np.array(val, copy=True)
            conn.send(("ok",))
        elif op == "push":
            key, val = req[1], req[2]
            if self.sync_mode:
                if key in self._merge:
                    self._merge[key] += val
                else:
                    self._merge[key] = np.array(val, copy=True)
                self._merge_count[key] = self._merge_count.get(key, 0) + 1
                self._waiting.setdefault(key, []).append(conn)
                if self._merge_count[key] == self.n_workers:
                    self._apply(key, self._merge.pop(key))
                    self._merge_count[key] = 0
                    for c in self._waiting.pop(key):
                        c.send(("ok",))
            else:
                self._apply(key, val)
                conn.send(("ok",))
        elif op == "pull":
            key = req[1]
            if key not in self.store:
                conn.send(("err", "pull of uninitialized key %r" % (key,)))
            else:
                conn.send(("ok", self.store[key]))
        elif op == "set_optimizer":
            from . import optimizer as opt

            optimizer = pickle.loads(req[1])
            self.updater = _NumpyUpdater(optimizer)
            conn.send(("ok",))
        elif op == "set_sync":
            # rank-0 worker announces consistency mode (kvstore.cc:31-38
            # kSyncMode command)
            self.sync_mode = bool(req[1])
            conn.send(("ok",))
        elif op == "barrier":
            self._barrier_conns.append(conn)
            if len(self._barrier_conns) == self.n_workers:
                for c in self._barrier_conns:
                    c.send(("ok",))
                self._barrier_conns = []
        elif op == "stop":
            conn.send(("ok",))
            self._stop.set()
        else:
            conn.send(("err", "unknown op %r" % (op,)))

    # --- threads ----------------------------------------------------------
    def _reader(self, conn):
        try:
            while not self._stop.is_set():
                req = conn.recv()
                self._q.put((conn, req))
        except (EOFError, OSError):
            pass

    def _accept_loop(self, listener):
        while not self._stop.is_set():
            try:
                conn = listener.accept()
            except Exception:
                # failed handshake (port probe, wrong authkey) or transient
                # socket error must not kill the server — the reference
                # server likewise survives bad peers (ps-lite van keeps
                # accepting). Back off briefly so a persistently broken
                # listener (EMFILE etc.) can't busy-spin a core; stop when
                # the listener is closed on stop.
                if self._stop.is_set():
                    break
                time.sleep(0.05)
                continue
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def run(self):
        """Blocking server loop (reference KVStoreDistServer::Run)."""
        listener = Listener(self.address, authkey=_AUTH)
        self._ready.set()
        threading.Thread(target=self._accept_loop, args=(listener,),
                         daemon=True).start()
        while not self._stop.is_set():
            try:
                conn, req = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(conn, req)
            except (EOFError, OSError):
                pass
        listener.close()

    def start_background(self):
        """Run in a daemon thread (in-process servers for tests/notebooks)."""
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        self._ready.wait(timeout=10)
        return t


class _NumpyUpdater:
    """Server-side updater applying a framework optimizer to numpy weights
    (the reference server runs fused optimizer ops on its engine; here the
    server is a host process, so updates are numpy/jax-on-cpu)."""

    def __init__(self, optimizer):
        from . import ndarray as nd

        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self._nd = nd

    def __call__(self, key, grad, weight):
        nd = self._nd
        ikey = key if isinstance(key, int) else abs(hash(key)) % (10 ** 9)
        w = nd.array(weight)
        g = nd.array(grad)
        if key not in self.states:
            self.states[key] = self.optimizer.create_state(ikey, w)
        self.optimizer.update(ikey, w, g, self.states[key])
        weight[...] = w.asnumpy()


class PSClient:
    """Worker-side connection (reference ps::KVWorker ZPush/ZPull)."""

    def __init__(self, address=None):
        self.address = address or _uri()
        if self.address is None:
            raise MXNetError(
                "no parameter server configured: set MXNET_TPU_PS_URI "
                "(host:port) or DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT")
        self._conn = None
        self._lock = threading.Lock()

    def _connect(self):
        if self._conn is None:
            self._conn = Client(self.address, authkey=_AUTH)
        return self._conn

    def _rpc(self, *req):
        with self._lock:
            conn = self._connect()
            conn.send(req)
            resp = conn.recv()
        if resp[0] != "ok":
            raise MXNetError("ps error: %s" % (resp[1],))
        return resp[1] if len(resp) > 1 else None

    def init(self, key, value: np.ndarray):
        self._rpc("init", key, np.asarray(value))

    def push(self, key, value: np.ndarray):
        self._rpc("push", key, np.asarray(value))

    def pull(self, key) -> np.ndarray:
        return self._rpc("pull", key)

    def set_optimizer(self, optimizer):
        self._rpc("set_optimizer", pickle.dumps(optimizer))

    def set_sync(self, sync: bool):
        self._rpc("set_sync", sync)

    def barrier(self):
        self._rpc("barrier")

    def stop(self):
        self._rpc("stop")


def run():
    """Entry for server-role processes: block until stopped (reference
    python/mxnet/kvstore_server.py:26-67 _init_kvstore_server_module)."""
    server = KVStoreServer()
    server.run()


def maybe_run_server_by_role():
    """Auto-start when launched with a server role, as the reference does
    at import (kvstore_server.py module bottom)."""
    if role() == "server":
        run()
        return True
    return False
