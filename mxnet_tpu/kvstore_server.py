"""Parameter-server service: the ps-lite functional equivalent.

Parity with src/kvstore/kvstore_dist_server.h + python/mxnet/
kvstore_server.py (SURVEY §2.1 #25-26, §3.4). The collective (`dist_sync`)
path of this framework needs no servers — gradients all-reduce in-graph
over ICI/DCN (parallel/). This module exists for the OTHER capability the
reference's PS provides: **asynchronous** (Hogwild) and hierarchical
updates where an optimizer step runs on merged gradients *outside* the
training step, plus the worker/server/scheduler process topology that
`tools/launch.py` spawns.

Design (host-side, CPU — weights live on servers, as in the reference):

- Transport: `multiprocessing.connection` (stdlib) instead of ZeroMQ.
  One `Listener` per server; each worker holds one duplex connection.
  Messages are framed as a small pickled CONTROL header followed by raw
  length-prefixed tensor payloads (`send_bytes` / `recv_bytes_into`
  straight into a preallocated numpy buffer) — the ps-lite `SArray`
  zero-copy analogue. Tensor bytes never pass through pickle: no
  serialize/copy on the hot push/pull path, and a tensor payload cannot
  smuggle a pickle payload. Control messages (op names, keys,
  set_optimizer's optimizer blob — the reference pickles that too) stay
  pickled.
- Server loop: connection-handler threads enqueue requests onto a single
  dispatch queue consumed by ONE thread — the reference's single-thread
  `Executor` run loop (kvstore_dist_server.h:28-85), which serializes all
  state mutation (no locks on the store itself).
- Sync mode: pushes accumulate into a per-key merge buffer; the updater
  runs once when all `num_workers` contributions arrived, then every
  waiting worker gets its reply — exactly DataHandle sync
  (kvstore_dist_server.h:164-198). Async mode applies immediately
  (:199-207).
- `set_optimizer` ships a pickled optimizer to the server, as the
  reference pickles through `_send_command_to_servers`
  (python/mxnet/kvstore.py set_optimizer).

Role selection mirrors the reference's import-time dispatch
(python/mxnet/kvstore_server.py:26-67): a process with
MXNET_TPU_ROLE/DMLC_ROLE == "server" calls `run()` and blocks until a
worker sends stop.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import queue
from typing import Any, Dict, Optional

import numpy as np
from multiprocessing.connection import Client, Listener

from .base import MXNetError
from . import telemetry
from .telemetry import context as _trace_context

_AUTH = b"mxnet_tpu_ps"
# header marker for a tensor slot: replaced by (marker, dtype, shape) in
# the pickled control header; the raw bytes follow as separate frames
_ND = "__ndarray_frame__"


def _trace_header() -> Optional[str]:
    """Outgoing W3C traceparent when the calling thread carries a trace
    context, else None — the PS plane's trace-carry header. Spans-off
    cost on every RPC: one thread-local read."""
    ctx = _trace_context.current_context()
    return None if ctx is None else _trace_context.to_traceparent(ctx)


def _traced(req: tuple) -> tuple:
    """Wrap a client request as ``("__traced__", traceparent, *req)``
    when a trace context is live; pass through untouched otherwise."""
    tp = _trace_header()
    return req if tp is None else ("__traced__", tp) + tuple(req)


def send_msg(conn, *parts):
    """Frame a message: pickled control header (ndarray parts replaced
    by (marker, dtype, shape) descriptors) + one raw frame per tensor."""
    head, tensors = [], []
    for p in parts:
        if isinstance(p, np.ndarray):
            t = np.ascontiguousarray(p)
            head.append((_ND, str(t.dtype), t.shape))
            tensors.append(t)
        else:
            head.append(p)
    conn.send_bytes(pickle.dumps(tuple(head)))
    for t in tensors:
        # empty multi-dim arrays can't be memoryview-cast (zeros in
        # shape); recv_msg special-cases size==0 symmetrically
        conn.send_bytes(memoryview(t).cast("B") if t.size else b"")


def recv_msg(conn):
    """Inverse of send_msg: tensor frames land via recv_bytes_into in
    freshly allocated numpy buffers — no pickle on tensor bytes."""
    head = pickle.loads(conn.recv_bytes())
    out = []
    for p in head:
        if isinstance(p, tuple) and len(p) == 3 and p[0] == _ND:
            buf = np.empty(p[2], dtype=np.dtype(p[1]))
            if buf.size:
                conn.recv_bytes_into(memoryview(buf).cast("B"))
            else:
                conn.recv_bytes()
            out.append(buf)
        else:
            out.append(p)
    return tuple(out)


def _uris():
    """All configured server addresses. MXNET_TPU_PS_URI is a
    comma-separated host:port list — one entry per server process (the
    reference's ps-lite server group, kvstore_dist.h GetServerKeyRanges)."""
    uri = os.environ.get("MXNET_TPU_PS_URI") or os.environ.get(
        "DMLC_PS_ROOT_URI")
    if uri is None:
        return None
    out = []
    for part in uri.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, port = part.rsplit(":", 1)
        else:
            host, port = part, os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        out.append((host, int(port)))
    return out or None


def _uri():
    uris = _uris()
    return uris[server_id() % len(uris)] if uris else None


def server_id() -> int:
    """This server process's index into the URI list."""
    return int(os.environ.get("MXNET_TPU_SERVER_ID",
                              os.environ.get("DMLC_SERVER_ID", "0")))


def bigarray_bound() -> int:
    """Arrays with more elements than this are split evenly across ALL
    servers (reference MXNET_KVSTORE_BIGARRAY_BOUND,
    kvstore_dist.h:276-314)."""
    return int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", "1000000"))


def role() -> str:
    return os.environ.get("MXNET_TPU_ROLE",
                          os.environ.get("DMLC_ROLE", "worker"))


def num_workers() -> int:
    return int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                              os.environ.get("DMLC_NUM_WORKER", "1")))


class KVStoreServer:
    """One server process's state + run loop."""

    def __init__(self, address=None, n_workers: Optional[int] = None,
                 sync_mode: bool = True):
        self.address = address or _uri() or ("127.0.0.1", 9091)
        self.n_workers = n_workers or num_workers()
        self.sync_mode = sync_mode
        self.store: Dict[Any, np.ndarray] = {}
        self.updater = None
        # sync-mode merge state, per key. Rank-tagged pushes (the PS
        # kvstore always tags) keep one contribution PER RANK so a worker
        # that died after its push was merged and rejoins (recovery)
        # REPLACES its stale contribution instead of being counted twice
        # — latest-wins per sender, the ps-lite per-sender dedupe
        # semantic. Untagged pushes (rank None, bare PSClient users)
        # fall back to arrival counting as before.
        self._merge_parts: Dict[Any, Dict[Any, np.ndarray]] = {}
        self._merge_anon: Dict[Any, np.ndarray] = {}
        self._merge_anon_count: Dict[Any, int] = {}
        self._waiting: Dict[Any, list] = {}  # [(rank_or_None, conn)]
        self._barrier_conns: list = []
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._ready = threading.Event()
        # liveness registry (the ps-lite heartbeat/GetDeadNodes analogue,
        # reference kvstore_dist.h:159-168): rank -> {conn, last_seen,
        # dead_since}. Registration/heartbeats ride each worker's control
        # connection; a dropped control connection marks the rank dead
        # until it re-registers (hello), which is how a restarted worker
        # is recognized as a recovery (kvstore_dist.h:39-42).
        self._workers: Dict[int, Dict[str, Any]] = {}

    # --- request handling (single dispatch thread) ------------------------
    def _apply(self, key, merged):
        if self.updater is not None:
            if key not in self.store:
                self.store[key] = np.zeros_like(merged)
            self.updater(key, merged, self.store[key])
        else:
            self.store[key] = np.array(merged, copy=True)

    def _handle(self, conn, req):
        op = req[0]
        if op == "__traced__":
            # trace carry from PSClient: ("__traced__", traceparent,
            # *inner). The server-side span is a CHILD of the worker's
            # calling span (parse mints a fresh span_id parented on the
            # header's), so a request's tree shows its PS hops once the
            # per-process ring files are merged (profiler.dump_profile).
            tp, inner = req[1], req[2:]
            if telemetry.enabled("kvstore"):
                ctx = _trace_context.parse_traceparent(tp)
                if ctx is not None:
                    with telemetry.span("kvstore.%s" % (inner[0],),
                                        domain="kvstore", **ctx.stamps()):
                        return self._handle(conn, inner)
            return self._handle(conn, inner)
        if op in ("push", "pull"):  # MXNET_FAULT_PLAN: delayed replies
            from .resilience import faults

            faults.maybe_delay("ps_server_%s" % op)
        if op == "init":
            key, val = req[1], req[2]
            if key not in self.store:  # first init wins (rank-0 semantics)
                self.store[key] = np.array(val, copy=True)
            send_msg(conn, "ok")
        elif op == "push":
            key, val = req[1], req[2]
            rank = req[3] if len(req) > 3 else None
            if self.sync_mode:
                waiting = self._waiting.setdefault(key, [])
                if rank is None:
                    if key in self._merge_anon:
                        self._merge_anon[key] += val
                    else:
                        self._merge_anon[key] = np.array(val, copy=True)
                    self._merge_anon_count[key] = \
                        self._merge_anon_count.get(key, 0) + 1
                    waiting.append((None, conn))
                else:
                    parts = self._merge_parts.setdefault(key, {})
                    if rank in parts:
                        # duplicate from the same sender (a recovered
                        # worker re-pushing the round its first attempt
                        # died in): replace, don't double-count — and
                        # drop the dead attempt's waiting reply slot
                        waiting[:] = [(r, c) for r, c in waiting
                                      if r != rank]
                    parts[rank] = np.array(val, copy=True)
                    waiting.append((rank, conn))
                n_got = (len(self._merge_parts.get(key, {}))
                         + self._merge_anon_count.get(key, 0))
                if n_got == self.n_workers:
                    merged = self._merge_anon.pop(key, None)
                    for part in self._merge_parts.pop(key, {}).values():
                        merged = (np.array(part, copy=True)
                                  if merged is None else merged + part)
                    self._merge_anon_count[key] = 0
                    self._apply(key, merged)
                    for _, c in self._waiting.pop(key):
                        # one dead worker's connection must not abort
                        # the replies to the LIVE waiters
                        try:
                            send_msg(c, "ok")
                        except (OSError, EOFError, BrokenPipeError):
                            pass
            else:
                self._apply(key, val)
                send_msg(conn, "ok")
        elif op == "pull":
            key = req[1]
            if key not in self.store:
                send_msg(conn, "err", "pull of uninitialized key %r" % (key,))
            else:
                send_msg(conn, "ok", self.store[key])
        elif op == "set_optimizer":
            from . import optimizer as opt

            optimizer = pickle.loads(req[1])
            self.updater = _NumpyUpdater(optimizer)
            send_msg(conn, "ok")
        elif op == "set_sync":
            # rank-0 worker announces consistency mode (kvstore.cc:31-38
            # kSyncMode command)
            self.sync_mode = bool(req[1])
            send_msg(conn, "ok")
        elif op == "barrier":
            self._barrier_conns.append(conn)
            if len(self._barrier_conns) == self.n_workers:
                for c in self._barrier_conns:
                    try:
                        send_msg(c, "ok")
                    except (OSError, EOFError, BrokenPipeError):
                        pass
                self._barrier_conns = []
        elif op == "hello":
            # worker registration on its control connection. A rank that
            # was seen before and is currently dead (conn dropped) comes
            # back as a RECOVERY — the reply tells the worker to skip the
            # startup barrier and pull current weights (server weights
            # are authoritative, reference kvstore_dist.h:39-42,77-79).
            rank = int(req[1])
            w = self._workers.get(rank)
            is_recovery = bool(w) and (w.get("dead_since") is not None
                                       or w.get("conn") is not conn)
            self._workers[rank] = {"conn": conn, "last_seen": time.time(),
                                   "dead_since": None}
            send_msg(conn, "ok", "recovery" if is_recovery else "welcome")
        elif op == "heartbeat":
            rank = int(req[1])
            w = self._workers.get(rank)
            if w is not None and w.get("conn") is conn:
                w["last_seen"] = time.time()
                w["dead_since"] = None
            send_msg(conn, "ok")
        elif op == "dead_nodes":
            # GetDeadNodes(timeout): ranks whose control connection
            # dropped (and no re-hello yet) or whose last heartbeat is
            # older than timeout seconds
            timeout = float(req[1])
            now = time.time()
            dead = sorted(rank for rank, w in self._workers.items()
                          if w.get("dead_since") is not None
                          or now - w.get("last_seen", now) > timeout)
            send_msg(conn, "ok", dead)
        elif op == "__disconnect__":
            # internal: a reader thread saw EOF on `conn`; if it was a
            # registered worker's control connection, mark the rank dead
            for w in self._workers.values():
                if w.get("conn") is conn and w.get("dead_since") is None:
                    w["dead_since"] = time.time()
        elif op == "stop":
            send_msg(conn, "ok")
            self._stop.set()
        else:
            send_msg(conn, "err", "unknown op %r" % (op,))

    # --- threads ----------------------------------------------------------
    def _reader(self, conn):
        try:
            while not self._stop.is_set():
                req = recv_msg(conn)
                self._q.put((conn, req))
        except (EOFError, OSError):
            # liveness: let the dispatch thread mark the rank (if any)
            # whose control connection this was
            self._q.put((conn, ("__disconnect__",)))

    def _accept_loop(self, listener):
        while not self._stop.is_set():
            try:
                conn = listener.accept()
            except Exception:
                # failed handshake (port probe, wrong authkey) or transient
                # socket error must not kill the server — the reference
                # server likewise survives bad peers (ps-lite van keeps
                # accepting). Back off briefly so a persistently broken
                # listener (EMFILE etc.) can't busy-spin a core; stop when
                # the listener is closed on stop.
                if self._stop.is_set():
                    break
                time.sleep(0.05)
                continue
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def run(self):
        """Blocking server loop (reference KVStoreDistServer::Run)."""
        listener = Listener(self.address, authkey=_AUTH)
        self._ready.set()
        threading.Thread(target=self._accept_loop, args=(listener,),
                         daemon=True).start()
        while not self._stop.is_set():
            try:
                conn, req = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(conn, req)
            except (EOFError, OSError):
                pass
        listener.close()
        # flush this process's span ring for the worker-side merge
        # (profiler.dump_profile); no-op unless MXNET_TELEMETRY_RING_DIR
        telemetry.dump_ring()

    def start_background(self):
        """Run in a daemon thread (in-process servers for tests/notebooks)."""
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        self._ready.wait(timeout=10)
        return t


class _NumpyUpdater:
    """Server-side updater applying a framework optimizer to numpy weights
    (the reference server runs fused optimizer ops on its engine; here the
    server is a host process, so updates are numpy/jax-on-cpu)."""

    def __init__(self, optimizer):
        from . import ndarray as nd

        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self._nd = nd

    def __call__(self, key, grad, weight):
        nd = self._nd
        ikey = key if isinstance(key, int) else abs(hash(key)) % (10 ** 9)
        w = nd.array(weight)
        g = nd.array(grad)
        if key not in self.states:
            self.states[key] = self.optimizer.create_state(ikey, w)
        self.optimizer.update(ikey, w, g, self.states[key])
        weight[...] = w.asnumpy()


class PSClient:
    """Worker-side connections to the server group (reference
    ps::KVWorker ZPush/ZPull + the EncodeKey sharding scheme,
    kvstore_dist.h:276-314): small keys go whole to one hashed server;
    arrays with more than ``bigarray_bound()`` elements are split into
    near-equal contiguous ranges, one per server, so no single server
    carries a whole embedding-sized tensor."""

    def __init__(self, addresses=None, rank=None):
        if (isinstance(addresses, tuple) and len(addresses) == 2
                and isinstance(addresses[0], str)):
            addresses = [addresses]  # single (host, port)
        # rank tags this client's sync-mode pushes so the server merges
        # one contribution PER SENDER (latest wins — recovery-safe);
        # None (bare clients) falls back to arrival counting
        self.rank = rank
        self.addresses = addresses or _uris()
        if not self.addresses:
            raise MXNetError(
                "no parameter server configured: set MXNET_TPU_PS_URI "
                "(comma-separated host:port list) or "
                "DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT")
        self._conns = [None] * len(self.addresses)
        # per-connection locks: a slow-to-bind server's connect retry must
        # not block RPCs to servers that are already up
        self._locks = [threading.Lock() for _ in self.addresses]
        # dedicated CONTROL connection to server 0 for hello/heartbeat/
        # dead_nodes (the ps-lite van/heartbeat channel analogue): liveness
        # queries must work while a sync-mode push is BLOCKED holding a
        # data connection's lock — that is exactly when survivors ask
        self._ctrl = None
        self._ctrl_lock = threading.Lock()

    @property
    def n_servers(self) -> int:
        return len(self.addresses)

    def _ensure_conn(self, sid):
        """Connect (caller holds self._locks[sid]); retry until the server
        binds — launchers start workers and servers concurrently, and
        ps-lite likewise reconnects. Backoff/deadline come from the one
        RetryPolicy (MXNET_TPU_PS_CONNECT_TIMEOUT + MXNET_TPU_PS_RETRY_*)."""
        conn = self._conns[sid]
        if conn is None:
            from .resilience.retry import RetryPolicy

            conn = RetryPolicy.for_connect().call(
                lambda: Client(self.addresses[sid], authkey=_AUTH),
                retry_on=(ConnectionRefusedError, FileNotFoundError,
                          OSError),
                what="connect to ps server %s" % (self.addresses[sid],))
            self._conns[sid] = conn
        return conn

    def _inject(self, op, sid=None):
        """MXNET_FAULT_PLAN hooks for the PS data path: an armed
        ``conn_drop`` severs the (data or control) connection exactly as
        a dying server would — the raised OSError travels the real
        failure path; ``delay`` simulates a slow reply."""
        from .resilience import faults

        faults.maybe_delay(op)
        if faults.maybe_drop(op):
            if sid is None:
                conn, self._ctrl = self._ctrl, None
            else:
                conn, self._conns[sid] = self._conns[sid], None
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            raise OSError("injected conn_drop at %s" % op)

    @staticmethod
    def _check(resp):
        if resp[0] != "ok":
            raise MXNetError("ps error: %s" % (resp[1],))
        return resp[1] if len(resp) > 1 else None

    def _rpc(self, sid, *req):
        with self._locks[sid]:
            self._inject("ps_%s" % req[0], sid)
            conn = self._ensure_conn(sid)
            send_msg(conn, *_traced(req))
            resp = recv_msg(conn)
        return self._check(resp)

    def _sharded_rpc(self, reqs):
        """One request per server, pipelined: send ALL parts, then collect
        ALL replies — per-server latency overlaps (max, not sum), which is
        also what lets sync-mode pushes of different parts merge
        concurrently server-side. reqs: [(sid, req tuple)], one per sid."""
        sids = [sid for sid, _ in reqs]
        tp = _trace_header()  # one header for every shard of this call
        for sid in sorted(sids):
            self._locks[sid].acquire()
        try:
            conns = {sid: self._ensure_conn(sid) for sid in sids}
            for sid, req in reqs:
                if tp is not None:
                    req = ("__traced__", tp) + tuple(req)
                send_msg(conns[sid], *req)
            resps = [recv_msg(conns[sid]) for sid, _ in reqs]
        finally:
            for sid in sorted(sids, reverse=True):
                self._locks[sid].release()
        return [self._check(r) for r in resps]

    def _server_of(self, key) -> int:
        # stable across processes: the built-in hash() is salted per
        # process, which would send the same string key to different
        # servers from different workers (deadlock in sync mode)
        import zlib

        k = key if isinstance(key, int) else zlib.crc32(str(key).encode())
        return k % self.n_servers

    def _plan(self, key, size):
        """None for a whole-array key, else [(server, lo, hi)] flat
        ranges covering [0, size) — the server key ranges of the
        reference's EncodeKey for big arrays."""
        n = self.n_servers
        if n == 1 or size <= bigarray_bound():
            return None
        per, rem = divmod(size, n)
        plan, off = [], 0
        for i in range(n):
            ln = per + (1 if i < rem else 0)
            plan.append((i, off, off + ln))
            off += ln
        return plan

    def init(self, key, value: np.ndarray):
        v = np.ascontiguousarray(value)
        plan = self._plan(key, v.size)
        if plan is None:
            self._rpc(self._server_of(key), "init", key, v)
            return
        flat = v.reshape(-1)
        self._sharded_rpc([(sid, ("init", (key, "part", sid), flat[lo:hi]))
                           for sid, lo, hi in plan])

    def push(self, key, value: np.ndarray):
        v = np.ascontiguousarray(value)
        plan = self._plan(key, v.size)
        if plan is None:
            self._rpc(self._server_of(key), "push", key, v, self.rank)
            return
        flat = v.reshape(-1)
        self._sharded_rpc([(sid, ("push", (key, "part", sid), flat[lo:hi],
                                  self.rank))
                           for sid, lo, hi in plan])

    def pull(self, key, size=None) -> np.ndarray:
        """size (element count) decides the shard plan exactly as on the
        push side; returns a FLAT array for sharded keys (the caller
        reshapes to its buffer — KVStoreDist::Pull into recv_buf)."""
        plan = None if size is None else self._plan(key, size)
        if plan is None:
            return self._rpc(self._server_of(key), "pull", key)
        parts = self._sharded_rpc([(sid, ("pull", (key, "part", sid)))
                                   for sid, lo, hi in plan])
        return np.concatenate([np.asarray(p).reshape(-1) for p in parts])

    def _ctrl_rpc(self, *req):
        with self._ctrl_lock:
            self._inject("ps_ctrl_%s" % req[0])
            if self._ctrl is None:
                from .resilience.retry import RetryPolicy

                self._ctrl = RetryPolicy.for_connect().call(
                    lambda: Client(self.addresses[0], authkey=_AUTH),
                    retry_on=(ConnectionRefusedError, FileNotFoundError,
                              OSError),
                    what="connect ps control channel %s"
                         % (self.addresses[0],))
            send_msg(self._ctrl, *req)
            resp = recv_msg(self._ctrl)
        return self._check(resp)

    def hello(self, rank: int) -> str:
        """Register this worker's liveness on the control channel; returns
        "welcome" (first join) or "recovery" (this rank was seen before
        and is currently dead — skip the startup barrier and pull current
        weights, reference kvstore_dist.h:39-42)."""
        return self._ctrl_rpc("hello", int(rank))

    def heartbeat(self, rank: int):
        self._ctrl_rpc("heartbeat", int(rank))

    def dead_nodes(self, timeout_sec: float = 60):
        """Ranks currently considered dead (dropped control connection or
        stale heartbeat) — reference GetDeadNodes, kvstore_dist.h:159."""
        return list(self._ctrl_rpc("dead_nodes", float(timeout_sec)))

    def set_optimizer(self, optimizer):
        blob = pickle.dumps(optimizer)
        for sid in range(self.n_servers):
            self._rpc(sid, "set_optimizer", blob)

    def set_sync(self, sync: bool):
        for sid in range(self.n_servers):
            self._rpc(sid, "set_sync", sync)

    def barrier(self):
        # worker-group barrier rides server 0; per-key sync merging makes
        # per-server barriers unnecessary (kvstore_dist_server.h sync mode)
        self._rpc(0, "barrier")

    def stop(self):
        for sid in range(self.n_servers):
            self._rpc(sid, "stop")


def run():
    """Entry for server-role processes: block until stopped (reference
    python/mxnet/kvstore_server.py:26-67 _init_kvstore_server_module)."""
    server = KVStoreServer()
    server.run()


def maybe_run_server_by_role():
    """Auto-start when launched with a server role, as the reference does
    at import (kvstore_server.py module bottom)."""
    if role() == "server":
        run()
        return True
    return False
