"""mxnet_tpu.progcache — persistent on-disk cache of compiled XLA programs.

Both the XLA operator-fusion study and TVM (PAPERS.md) argue that the
small set of shape-specialized compiled programs IS the framework's
performance asset — yet every process start used to rebuild that asset
from scratch: a restarted ``InferenceServer`` suffered a cold-start
compile storm across its whole bucket ladder, and every train job
re-lowered and re-compiled its fused step. This module persists the
asset:

- **Content-addressed entries.** Each cached program is one file,
  ``<key>.prog``, where ``key`` is a sha1 over (model fingerprint,
  input names/shapes/dtypes, backend + device kind, jax/jaxlib/package
  versions, donation config). The *model fingerprint* for a Predictor
  hashes the symbol JSON plus every parameter's name/shape/dtype/CRC —
  parameter values are closure-baked constants inside the serialized
  executable, so a cache hit with different weights would silently serve
  a stale model; hashing the bytes makes that a miss instead. The train
  step's ``update_fn`` is arbitrary Python, so its key hashes the
  lowered StableHLO text (the only faithful capture of the program).
- **Self-verifying entry format.** ``MXTPUPROG\\x01`` magic, a JSON meta
  block (versions, backend, key), a CRC32 of the payload, then the
  payload: the pickled ``(bytes, in_tree, out_tree)`` triple from
  ``jax.experimental.serialize_executable``. Loads verify magic, meta,
  version skew, and CRC before deserializing; ANY failure (truncation,
  corruption, skew, deserialize error) is a silent fallback to a fresh
  compile, counted in ``progcache_fallbacks``. The cache can only make
  startup faster, never answers wrong.
- **Atomic commits.** Every file write goes through
  :func:`_atomic_write_bytes` — tmp + fsync + ``os.replace``, the same
  commit idiom as ``resilience.checkpoint`` — so a crash mid-write can
  never leave a half-entry at the committed name. The analysis stage-7
  checker ``progcache_io`` enforces this for the module.
- **CRC-checked manifest + LRU byte budget.** ``manifest.json`` holds
  per-entry byte sizes and LRU clocks plus persisted bucket ladders;
  it is advisory — corruption or cross-process races rebuild it from a
  directory scan (entries are content-addressed, the manifest is never
  needed for correctness). Total bytes are bounded by
  ``MXNET_PROGCACHE_BYTES`` (default 2 GiB), evicting oldest-clock
  entries first.

Enablement: the cache is OFF unless ``MXNET_PROGCACHE_DIR`` is set (or
``MXNET_PROGCACHE=1``, which uses ``~/.cache/mxnet_tpu/progcache``);
``MXNET_PROGCACHE=0`` is the kill switch that wins over everything.
Sharing one cache dir across replicas/processes is supported: commits
are atomic renames, loads go straight to the content-addressed file,
and manifest races are last-writer-wins on advisory data only.

Telemetry: ``progcache_hits`` / ``progcache_misses`` /
``progcache_fallbacks`` counters and a ``progcache_bytes`` gauge in the
unified registry, plus ``progcache.load`` / ``progcache.store`` tracer
spans (domain ``progcache``).
"""
from __future__ import annotations

import binascii
import hashlib
import json
import logging
import os
import pickle
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from . import telemetry as _telemetry

log = logging.getLogger("mxnet_tpu")

MAGIC = b"MXTPUPROG\x01"
MANIFEST = "manifest.json"
MANIFEST_VERSION = 1
DEFAULT_BUDGET = 2 << 30  # 2 GiB
_U32 = struct.Struct("<I")

# Serializes manifest read-modify-write and the session stat dict.
# Declared leaf (rank 100) in analysis.lockorder.LOCK_HIERARCHY: nothing
# ranked is ever acquired under it, and telemetry increments happen
# outside holds of it.
_lock = threading.Lock()

# Session counters (mirrored into the telemetry registry; kept here too so
# stats() works even with MXNET_TELEMETRY=0).
_stats = {"hits": 0, "misses": 0, "fallbacks": 0, "stores": 0,
          "evictions": 0}

# Bytes in use per cache dir, refreshed on every manifest load/commit —
# the progcache_bytes gauge reads this instead of hitting the disk.
_bytes_by_dir: Dict[str, int] = {}

# Same, split by entry kind (predictor / train_step / fused / "" for
# legacy entries) — a per-kind gauge is registered lazily when a kind
# first appears so the exposition only grows for kinds actually in use.
_bytes_by_dir_kind: Dict[str, Dict[str, int]] = {}
_kind_gauges: Dict[str, object] = {}

_hits = _telemetry.registry.counter(
    "progcache_hits", "persistent program cache: successful disk loads")
_misses = _telemetry.registry.counter(
    "progcache_misses", "persistent program cache: key not present")
_fallbacks = _telemetry.registry.counter(
    "progcache_fallbacks",
    "persistent program cache: entry present but unusable "
    "(corruption/version skew/deserialize failure) — fell back to compile")
_telemetry.registry.gauge(
    "progcache_bytes", lambda: float(sum(_bytes_by_dir.values())),
    "persistent program cache: bytes on disk (all dirs used this process)")


# --- enablement -----------------------------------------------------------

def cache_dir() -> Optional[str]:
    """The active cache directory, or None when the cache is disabled.

    Read at point of use (like the telemetry kill switch) so tests and
    operators can flip it per-process without code changes."""
    flag = os.environ.get("MXNET_PROGCACHE", "").strip().lower()
    if flag in ("0", "off", "false", "none"):
        return None  # kill switch wins over MXNET_PROGCACHE_DIR
    d = os.environ.get("MXNET_PROGCACHE_DIR", "").strip()
    if d:
        return d
    if flag in ("1", "on", "true"):
        return os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu",
                            "progcache")
    return None


def enabled() -> bool:
    return cache_dir() is not None


def byte_budget() -> int:
    try:
        return int(os.environ.get("MXNET_PROGCACHE_BYTES", DEFAULT_BUDGET))
    except ValueError:
        return DEFAULT_BUDGET


# --- atomic commit (the resilience.checkpoint idiom) ----------------------

def _atomic_write_bytes(path: str, data: bytes):
    """tmp + fsync + os.replace: the committed name either holds the old
    content or the complete new content, never a torn write. The ONLY
    function in this module allowed to open files for writing (enforced
    by the ``progcache_io`` analysis checker)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# --- fingerprints / keys --------------------------------------------------

def _runtime_meta() -> Dict[str, str]:
    """The environment facts a cached executable is only valid under."""
    import jax
    import jaxlib

    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    from .base import __version__ as pkg_version
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "mxnet_tpu": pkg_version,
        "backend": jax.default_backend(),
        "device_kind": kind,
    }


def _param_digest(h, name: str, arr) -> None:
    """Fold one parameter into ``h``: name/shape/dtype AND a CRC of the
    bytes. Values matter — jit closure constants are baked into the
    serialized executable, so two weight sets must never share a key."""
    import numpy as np

    data = np.asarray(getattr(arr, "_data", arr))
    h.update(name.encode())
    h.update(str(data.shape).encode())
    h.update(str(data.dtype).encode())
    h.update(_U32.pack(binascii.crc32(data.tobytes()) & 0xFFFFFFFF))


def model_fingerprint(symbol, arg_params: Dict, aux_params: Dict) -> str:
    """sha1 over the symbol graph + every parameter's name/shape/dtype/CRC.
    This is the 'same model, same weights' identity that predictor keys
    and persisted ladders hang off."""
    h = hashlib.sha1()
    h.update(symbol.tojson().encode())
    for name in sorted(arg_params):
        _param_digest(h, "arg:" + name, arg_params[name])
    for name in sorted(aux_params):
        _param_digest(h, "aux:" + name, aux_params[name])
    return h.hexdigest()


def predictor_key(model_fp: str, input_names: Sequence[str],
                  input_shapes: Dict[str, tuple], dtype: str,
                  device: Optional[object] = None) -> str:
    """Cache key for a Predictor program: model identity + the bound
    input signature + the runtime facts. Computable WITHOUT lowering —
    warm hits skip jax.jit/lower entirely, which is what makes a warm
    restart ≥3× faster than a cold one."""
    h = hashlib.sha1()
    h.update(b"predict\x00")
    h.update(model_fp.encode())
    for n in input_names:
        h.update(n.encode())
        h.update(str(tuple(input_shapes[n])).encode())
    h.update(str(dtype).encode())
    if device is not None:
        h.update(repr(device).encode())
    h.update(json.dumps(_runtime_meta(), sort_keys=True).encode())
    return h.hexdigest()


def lowered_key(lowered_text: str, donate: Sequence[int] = (),
                extra: str = "") -> str:
    """Cache key for an arbitrary lowered computation (the fused train
    step): ``update_fn`` is arbitrary Python, so only the lowered
    StableHLO text captures it faithfully. Donation config is part of the
    key — a donating and a non-donating compile of the same HLO are
    different programs."""
    h = hashlib.sha1()
    h.update(b"lowered\x00")
    h.update(lowered_text.encode())
    h.update(str(tuple(donate)).encode())
    if extra:
        h.update(extra.encode())
    h.update(json.dumps(_runtime_meta(), sort_keys=True).encode())
    return h.hexdigest()


def fused_key(capture_sig: str, lowered_text: Optional[str] = None) -> str:
    """Cache key for a trace-and-fused CapturedSequence (engine
    ``FusedSequence``): sha1 over the capture signature — per-op
    fingerprints, the resolved edge set and in/out avals, already
    normalized to process-independent var indices — plus the lowered
    StableHLO text when any op had no explicit fingerprint, plus the
    runtime facts. Warm restarts of the same captured program re-derive
    the same key and disk-load with zero fresh compiles."""
    h = hashlib.sha1()
    h.update(b"fused\x00")
    h.update(capture_sig.encode())
    if lowered_text is not None:
        h.update(b"\x00text\x00")
        h.update(lowered_text.encode())
    h.update(json.dumps(_runtime_meta(), sort_keys=True).encode())
    return h.hexdigest()


# --- manifest -------------------------------------------------------------

def _entries_crc(entries: Dict, ladders: Dict, clock: int) -> int:
    blob = json.dumps([entries, ladders, clock], sort_keys=True).encode()
    return binascii.crc32(blob) & 0xFFFFFFFF


def _entry_kind(path: str) -> str:
    """The ``kind`` from an entry file's meta header (manifest rebuild
    only reads the small header, never the payload)."""
    try:
        with open(path, "rb") as f:
            head = f.read(len(MAGIC) + 4)
            if not head.startswith(MAGIC):
                return ""
            (mlen,) = _U32.unpack_from(head, len(MAGIC))
            meta = json.loads(f.read(mlen).decode())
        return str(meta.get("kind", ""))
    except Exception:
        return ""


def _load_manifest(d: str) -> Dict:
    """Read + CRC-verify the manifest; rebuild from a directory scan when
    missing or corrupt (the manifest is advisory — entries are
    content-addressed, so a rebuild loses only LRU clocks/ladders)."""
    path = os.path.join(d, MANIFEST)
    try:
        with open(path, "rb") as f:
            m = json.loads(f.read().decode())
        if (m.get("version") == MANIFEST_VERSION and
                m.get("crc") == _entries_crc(m.get("entries", {}),
                                             m.get("ladders", {}),
                                             m.get("clock", 0))):
            return m
        log.warning("progcache: manifest CRC mismatch at %s — rebuilding",
                    path)
    except FileNotFoundError:
        pass
    except Exception as e:  # corrupt JSON, unreadable, ...
        log.warning("progcache: unreadable manifest at %s (%s) — rebuilding",
                    path, e)
    entries = {}
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    for fn in names:
        if fn.endswith(".prog"):
            try:
                sz = os.path.getsize(os.path.join(d, fn))
            except OSError:
                continue
            e = {"bytes": sz, "clock": 0}
            kind = _entry_kind(os.path.join(d, fn))
            if kind:
                e["kind"] = kind
            entries[fn[:-len(".prog")]] = e
    return {"version": MANIFEST_VERSION, "clock": 0, "entries": entries,
            "ladders": {}, "crc": _entries_crc(entries, {}, 0)}


def _refresh_kind_bytes(d: str, m: Dict):
    by_kind: Dict[str, int] = {}
    for e in m["entries"].values():
        k = e.get("kind", "")
        by_kind[k] = by_kind.get(k, 0) + e.get("bytes", 0)
    _bytes_by_dir_kind[d] = by_kind
    for k in by_kind:
        if k and k not in _kind_gauges:
            _kind_gauges[k] = _telemetry.registry.gauge(
                "progcache_bytes_kind_" + k,
                lambda _k=k: float(sum(
                    bk.get(_k, 0) for bk in _bytes_by_dir_kind.values())),
                "persistent program cache: bytes on disk for %r entries"
                % k)


def _commit_manifest(d: str, m: Dict):
    m["crc"] = _entries_crc(m["entries"], m.get("ladders", {}), m["clock"])
    _atomic_write_bytes(os.path.join(d, MANIFEST),
                        json.dumps(m, sort_keys=True).encode())
    _bytes_by_dir[d] = sum(e.get("bytes", 0) for e in m["entries"].values())
    _refresh_kind_bytes(d, m)


def _evict_over_budget(d: str, m: Dict, protect: str) -> List[str]:
    """Drop oldest-clock entries until total bytes fit the budget; the
    just-stored key is protected so a store is never a self-eviction."""
    budget = byte_budget()
    total = sum(e.get("bytes", 0) for e in m["entries"].values())
    victims: List[str] = []
    by_age = sorted((k for k in m["entries"] if k != protect),
                    key=lambda k: m["entries"][k].get("clock", 0))
    for k in by_age:
        if total <= budget:
            break
        total -= m["entries"][k].get("bytes", 0)
        del m["entries"][k]
        victims.append(k)
    for k in victims:
        try:
            os.remove(os.path.join(d, k + ".prog"))
        except OSError:
            pass
    return victims


# --- load / store ---------------------------------------------------------

def _entry_path(d: str, key: str) -> str:
    return os.path.join(d, key + ".prog")


def _pack_entry(meta: Dict, payload: bytes) -> bytes:
    mb = json.dumps(meta, sort_keys=True).encode()
    return b"".join([MAGIC, _U32.pack(len(mb)), mb,
                     _U32.pack(binascii.crc32(payload) & 0xFFFFFFFF),
                     payload])


def _unpack_entry(blob: bytes) -> Tuple[Dict, bytes]:
    """Parse + verify one entry file; raises ValueError on any damage."""
    if len(blob) < len(MAGIC) + 8 or not blob.startswith(MAGIC):
        raise ValueError("bad magic / truncated header")
    off = len(MAGIC)
    (mlen,) = _U32.unpack_from(blob, off)
    off += 4
    if len(blob) < off + mlen + 4:
        raise ValueError("truncated meta block")
    meta = json.loads(blob[off:off + mlen].decode())
    off += mlen
    (crc,) = _U32.unpack_from(blob, off)
    off += 4
    payload = blob[off:]
    if binascii.crc32(payload) & 0xFFFFFFFF != crc:
        raise ValueError("payload CRC mismatch")
    return meta, payload


def _check_meta(meta: Dict) -> Optional[str]:
    """None when the entry is valid in this process; else the skew."""
    want = _runtime_meta()
    for k, v in want.items():
        if meta.get(k) != v:
            return "%s %r != %r" % (k, meta.get(k), v)
    return None


def _count(which: str):
    with _lock:
        _stats[which] = _stats.get(which, 0) + 1
    if which == "hits":
        _hits.inc()
    elif which == "misses":
        _misses.inc()
    elif which == "fallbacks":
        _fallbacks.inc()


def _drop_bad_entry(d: str, key: str):
    """Best-effort removal of an entry that failed verification, so the
    fallback is paid once, not on every restart."""
    try:
        os.remove(_entry_path(d, key))
    except OSError:
        pass
    with _lock:
        m = _load_manifest(d)
        if key in m["entries"]:
            del m["entries"][key]
            try:
                _commit_manifest(d, m)
            except OSError:
                pass


def load(key: str, kind: str = ""):
    """The deserialized, loaded executable for ``key``, or None.

    None means 'compile fresh' — either a clean miss (counted in
    ``progcache_misses``) or a damaged/skewed entry (counted in
    ``progcache_fallbacks`` and deleted). Never raises. ``kind`` tags the
    hit for the compile witness (``analysis.compile_witness``) so disk
    loads are accounted per surface; empty skips the witness."""
    d = cache_dir()
    if d is None:
        return None
    path = _entry_path(d, key)
    with _telemetry.span("progcache.load", domain="progcache", key=key[:12]):
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            _count("misses")
            return None
        except OSError as e:
            log.warning("progcache: unreadable entry %s (%s)", path, e)
            _count("fallbacks")
            return None
        try:
            meta, payload = _unpack_entry(blob)
            skew = _check_meta(meta)
            if skew is not None:
                raise ValueError("version skew: %s" % skew)
            from jax.experimental import serialize_executable as _sx

            serialized, in_tree, out_tree = pickle.loads(payload)
            exe = _sx.deserialize_and_load(serialized, in_tree, out_tree)
        except Exception as e:
            log.warning("progcache: entry %s unusable (%s) — falling back "
                        "to fresh compile", path, e)
            _drop_bad_entry(d, key)
            _count("fallbacks")
            return None
    touch(key)
    _count("hits")
    if kind:
        from .analysis import compile_witness as _witness

        _witness.record_disk_load(kind, key=key)
    return exe


def store(key: str, compiled, note: str = "", kind: str = "") -> bool:
    """Serialize ``compiled`` and commit it under ``key`` atomically,
    then update the manifest and evict past the byte budget. ``kind``
    classifies the entry (``predictor`` / ``train_step`` / ``fused`` /
    ``decode`` / ``quant``) for the per-kind byte accounting. Best-effort: returns
    False (never raises) when serialization or I/O fails — the caller
    already has its compiled program either way."""
    d = cache_dir()
    if d is None:
        return False
    with _telemetry.span("progcache.store", domain="progcache",
                         key=key[:12]):
        try:
            from jax.experimental import serialize_executable as _sx

            serialized, in_tree, out_tree = _sx.serialize(compiled)
            payload = pickle.dumps((serialized, in_tree, out_tree),
                                   protocol=pickle.HIGHEST_PROTOCOL)
            meta = dict(_runtime_meta())
            meta["key"] = key
            if note:
                meta["note"] = note
            if kind:
                meta["kind"] = kind
            blob = _pack_entry(meta, payload)
            os.makedirs(d, exist_ok=True)
            _atomic_write_bytes(_entry_path(d, key), blob)
        except Exception as e:
            log.warning("progcache: store of %s failed (%s)", key[:12], e)
            return False
        victims: List[str] = []
        with _lock:
            m = _load_manifest(d)
            m["clock"] += 1
            entry = {"bytes": len(blob), "clock": m["clock"]}
            if kind:
                entry["kind"] = kind
            m["entries"][key] = entry
            victims = _evict_over_budget(d, m, protect=key)
            try:
                _commit_manifest(d, m)
            except OSError as e:
                log.warning("progcache: manifest commit failed (%s)", e)
            _stats["stores"] += 1
            _stats["evictions"] += len(victims)
    if victims:
        log.info("progcache: evicted %d entries over the %d-byte budget",
                 len(victims), byte_budget())
    return True


def touch(key: str):
    """Bump ``key``'s LRU clock (a hit, or a ladder retune keeping its
    bucket). Best-effort — advisory data only."""
    d = cache_dir()
    if d is None:
        return
    with _lock:
        m = _load_manifest(d)
        e = m["entries"].get(key)
        if e is None:
            return
        m["clock"] += 1
        e["clock"] = m["clock"]
        try:
            _commit_manifest(d, m)
        except OSError:
            pass


# --- persisted bucket ladders --------------------------------------------

def save_ladder(model_fp: str, buckets: Sequence[int]):
    """Persist a tuned bucket ladder for ``model_fp`` so a restarted
    server adopts it (and disk-loads exactly those programs) instead of
    rediscovering it from live traffic."""
    d = cache_dir()
    if d is None:
        return
    with _lock:
        m = _load_manifest(d)
        m.setdefault("ladders", {})[model_fp] = sorted(
            int(b) for b in buckets)
        try:
            os.makedirs(d, exist_ok=True)
            _commit_manifest(d, m)
        except OSError as e:
            log.warning("progcache: ladder save failed (%s)", e)


def load_ladder(model_fp: str) -> Optional[List[int]]:
    d = cache_dir()
    if d is None:
        return None
    with _lock:
        m = _load_manifest(d)
        lad = m.get("ladders", {}).get(model_fp)
    return [int(b) for b in lad] if lad else None


# --- introspection --------------------------------------------------------

def stats() -> Dict[str, int]:
    """Session counters (this process): hits/misses/fallbacks/stores/
    evictions."""
    with _lock:
        return dict(_stats)


def reset_stats():
    with _lock:
        for k in _stats:
            _stats[k] = 0


def bytes_in_use() -> int:
    """Bytes on disk in the active cache dir (from the manifest)."""
    d = cache_dir()
    if d is None:
        return 0
    with _lock:
        m = _load_manifest(d)
        total = sum(e.get("bytes", 0) for e in m["entries"].values())
        _bytes_by_dir[d] = total
        _refresh_kind_bytes(d, m)
    return total


def bytes_by_kind() -> Dict[str, int]:
    """Bytes on disk in the active cache dir split by entry ``kind``
    (``""`` collects entries stored before kinds existed)."""
    d = cache_dir()
    if d is None:
        return {}
    with _lock:
        m = _load_manifest(d)
        _refresh_kind_bytes(d, m)
        return dict(_bytes_by_dir_kind[d])
