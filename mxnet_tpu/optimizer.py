"""Optimizers.

Reimplementation of python/mxnet/optimizer.py (SURVEY §2.4): registry +
Optimizer base with lr/wd multipliers, the full zoo (SGD w/ momentum, NAG,
SGLD, ccSGD, DCASGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl, Test), and the
Updater with state (de)serialization used by KVStore.

The hot updates dispatch to the *fused* update ops
(ops/optimizer_ops.py ≡ src/operator/tensor/optimizer_op.cc) so the whole
step stays on device in one XLA computation.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "ccSGD", "DCASGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Test", "Updater",
           "create", "register", "get_updater"]

opt_registry: Dict[str, type] = {}


def register(klass):
    opt_registry[klass.__name__.lower()] = klass
    return klass



def cached_lr_wd_arrays(cache, lw, sharding=None):
    """(lr_arr, wd_arr, new_cache): re-upload the stacked lr/wd arrays only
    when the host-side values changed — shared by Updater.update_all and
    Module's fused fit step. `sharding` (e.g. replicated over the data
    mesh for the ZeRO-1 sharded update) commits the uploads to the mesh
    so the fused step isn't fed single-device arrays."""
    import jax
    import jax.numpy as jnp

    if cache is None or not np.array_equal(cache[0], lw):
        lr_arr, wd_arr = jnp.asarray(lw[:, 0]), jnp.asarray(lw[:, 1])
        if sharding is not None:
            lr_arr = jax.device_put(lr_arr, sharding)
            wd_arr = jax.device_put(wd_arr, sharding)
        cache = (lw, lr_arr, wd_arr)
    return cache[1], cache[2], cache


def state_leaves(state, copy=False):
    """Raw jax leaves of an optimizer state (None / NDArray / tuple of
    NDArrays) — shared by the batched updater and Module's fused fit step."""
    import jax.numpy as jnp

    def leaf(x):
        if x is None:
            return None
        return jnp.array(x._data, copy=True) if copy else x._data

    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(leaf(x) for x in state)
    return leaf(state)


def write_state_leaves(state, leaves):
    """Write raw leaves back into the state's NDArrays (inverse of
    state_leaves)."""
    if state is None:
        return
    if isinstance(state, tuple):
        for old, val in zip(state, leaves):
            if old is not None:
                old._data = val
    else:
        state._data = leaves


def _zeros_like_state(weight):
    """State buffer matching the weight's dtype AND (mesh) sharding, so fused
    updates run where the weight lives."""
    import jax.numpy as jnp

    return NDArray(jnp.zeros_like(weight._data))

class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        # bumped by set_lr_mult/set_wd_mult: fit_step's constant-lr cache
        # fingerprints on it (in-place mutation of the mult dicts must go
        # through the setters to be seen there)
        self._mult_version = 0
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        if sym is not None:
            attrs = sym.attr_dict()
            for name in sym.list_arguments():
                if name in attrs:
                    if "__lr_mult__" in attrs[name]:
                        self.lr_mult[name] = float(attrs[name]["__lr_mult__"])
                    if "__wd_mult__" in attrs[name]:
                        self.wd_mult[name] = float(attrs[name]["__wd_mult__"])

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in opt_registry:
            return opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def effective_lr_wd(self, index):
        """(lr, wd) actually applied for this key at the current step —
        schedule, lr/wd multipliers, and any step-count folding (Adam bias
        correction) resolved host-side so the device rule stays static."""
        return self._get_lr(index), self._get_wd(index)

    def pure_rule(self):
        """Return fn(w, g, state, lr, wd) -> (new_w, new_state), a pure
        traceable update with hyperparameters closed over, or None if this
        optimizer has no pure form (then the per-key eager path is used).
        lr/wd arrive as dynamic scalars so LR schedules don't retrace.
        Other hyperparameters (momentum, betas, rescale_grad, clip) are
        baked in at trace time — callers caching a compiled rule must
        re-trace if they mutate them (Updater.update_all keys its cache on
        Optimizer._hyperparam_key() for this reason).
        Enables Updater.update_all: the whole parameter tree updated in ONE
        jitted program — the analogue of the reference running its fused
        optimizer kernels (optimizer_op.cc) inside engine bulk segments."""
        return None

    def _pure_prep_grad(self, g, w, wd):
        import jax.numpy as jnp
        g = g * self.rescale_grad
        if self.clip_gradient is not None and self.clip_gradient > 0:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g + wd * w

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult.update(args_lr_mult)
        self._mult_version += 1

    def set_wd_mult(self, args_wd_mult):
        """Reference semantics (optimizer.py set_wd_mult): params whose name
        does not end in _weight/_gamma default to wd_mult 0, symbol attrs
        override, explicit args override both."""
        self._mult_version += 1
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attrs = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attrs and "__wd_mult__" in attrs[name]:
                    self.wd_mult[name] = float(attrs[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        name = self.idx2name.get(index, index)
        if name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if isinstance(name, str) and name not in self.wd_mult:
            # reference default: no decay for bias / bn params
            if name.endswith("_bias") or name.endswith("_gamma") or name.endswith("_beta"):
                wd = 0.0
        if name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _clip_attr(self):
        return -1.0 if self.clip_gradient is None else self.clip_gradient

    # attrs that either enter the jitted rule dynamically (lr/wd via the
    # stacked lr_arr/wd_arr) or are pure bookkeeping — everything else is
    # baked into pure_rule() at trace time and must invalidate caches.
    _DYNAMIC_OR_BOOKKEEPING = frozenset({
        "lr", "wd", "lr_scheduler", "lr_mult", "wd_mult", "idx2name",
        "sym", "num_update", "begin_num_update", "_index_update_count",
        # mult-dict version: consumed by fit_step's cheap lw fingerprint;
        # including it in the hyper key would turn every set_*_mult into
        # a full fused-step rebuild instead of a one-off lw recompute
        "_mult_version"})

    def _hyperparam_key(self):
        """Hashable tuple of every scalar hyperparameter closed over by
        pure_rule(). Updater.update_all keys its compiled-rule cache on this
        so mutating e.g. momentum/beta1 mid-training (a warmup schedule)
        re-traces instead of being silently ignored on the batched path."""
        items = []
        for k in sorted(vars(self)):
            if k in self._DYNAMIC_OR_BOOKKEEPING:
                continue
            v = getattr(self, k)
            if isinstance(v, np.generic):
                v = v.item()  # np.float32 etc. compare like Python scalars
            if v is None or isinstance(v, (int, float, bool, str)):
                items.append((k, v))
            else:
                # non-scalar hyperparam (array/list/...): key on repr so a
                # mutation still invalidates rather than silently vanishing
                items.append((k, repr(v)))
        return tuple(items)


# convenience alias (reference keeps `create` at module level)
def create(name, **kwargs):
    return Optimizer.create_optimizer(name, **kwargs)


@register
class SGD(Optimizer):
    """SGD with momentum using the fused sgd(_mom)_update kernels."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        attrs = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                 "clip_gradient": self._clip_attr()}
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **attrs)
        else:
            res = nd.sgd_mom_update(weight, grad, state, momentum=self.momentum, **attrs)
            weight._data = res[0]._data
            state._data = res[1]._data

    def pure_rule(self):
        mom = self.momentum

        def rule(w, g, s, lr, wd):
            g = self._pure_prep_grad(g, w, wd)
            if s is None:
                return w - lr * g, None
            m = mom * s - lr * g
            return w + m, m

        return rule


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py NAG)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom._data = (mom * self.momentum)._data
            g = g + wd * weight
            mom._data = (mom + g)._data
            g = g + self.momentum * mom
            weight._data = (weight - lr * g)._data
        else:
            weight._data = (weight - lr * (g + wd * weight))._data

    def pure_rule(self):
        mom = self.momentum

        def rule(w, g, s, lr, wd):
            g = self._pure_prep_grad(g, w, wd)  # rescale+clip+wd, as update()
            if s is None:
                return w - lr * g, None
            m = s * mom + g
            return w - lr * (g + mom * m), m

        return rule


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.array(
            np.random.normal(0, math.sqrt(lr), size=weight.shape).astype(np.float32),
            ctx=weight.context,
        )
        weight._data = (weight - (lr / 2) * (g + wd * weight) + noise)._data


@register
class ccSGD(SGD):
    """Kept for API parity (reference ccSGD is SGD with C++ impl)."""


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_zeros_like_state(weight), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mon, previous_weight = state
        comp = g + wd * weight + self.lamda * g * g * (weight - previous_weight)
        if mon is not None:
            mon._data = (self.momentum * mon - lr * comp)._data
            delta = mon
        else:
            delta = -lr * comp
        previous_weight._data = weight._data
        weight._data = (weight + delta)._data


@register
class Adam(Optimizer):
    """Adam using the fused adam_update kernel; bias correction folded into
    lr as in the reference (optimizer.py Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def effective_lr_wd(self, index):
        # fold bias correction into lr host-side (reference optimizer.py Adam)
        t = self._index_update_count.get(index, self.begin_num_update) or 1
        lr, wd = self._get_lr(index), self._get_wd(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        return lr * math.sqrt(coef2) / coef1, wd

    def pure_rule(self):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def rule(w, g, s, lr, wd):
            import jax.numpy as jnp
            mean, var = s
            g = self._pure_prep_grad(g, w, wd)
            mean_t = b1 * mean + (1 - b1) * g
            var_t = b2 * var + (1 - b2) * jnp.square(g)
            return w - lr * mean_t / (jnp.sqrt(var_t) + eps), (mean_t, var_t)

        return rule

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        res = nd.adam_update(
            weight, grad, mean, var, lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, rescale_grad=self.rescale_grad,
            clip_gradient=self._clip_attr(),
        )
        weight._data = res[0]._data
        mean._data = res[1]._data
        var._data = res[2]._data


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        history = state
        history._data = (history + g * g)._data
        weight._data = (weight - lr * (g / nd.sqrt(history + self.float_stable_eps) + wd * weight))._data

    def pure_rule(self):
        eps = self.float_stable_eps

        def rule(w, g, s, lr, wd):
            import jax.numpy as jnp
            g = g * self.rescale_grad
            if self.clip_gradient is not None and self.clip_gradient > 0:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            h = s + g * g
            return w - lr * (g / jnp.sqrt(h + eps) + wd * w), h

        return rule


@register
class RMSProp(Optimizer):
    """RMSProp; centered=True selects the Graves'13 variant, matching the
    fused rmsprop_update / rmspropalex_update split (optimizer.py RMSProp)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like_state(weight), _zeros_like_state(weight),
                    _zeros_like_state(weight))
        return (_zeros_like_state(weight),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                  "gamma1": self.gamma1, "epsilon": self.epsilon,
                  "clip_gradient": self._clip_attr(),
                  "clip_weights": self.clip_weights if self.clip_weights else -1.0}
        if not self.centered:
            (n,) = state
            res = nd.rmsprop_update(weight, grad, n, **kwargs)
            weight._data = res[0]._data
            n._data = res[1]._data
        else:
            n, g, delta = state
            res = nd.rmspropalex_update(weight, grad, n, g, delta,
                                        gamma2=self.gamma2, **kwargs)
            weight._data = res[0]._data
            n._data = res[1]._data
            g._data = res[2]._data
            delta._data = res[3]._data

    def pure_rule(self):
        g1, g2, eps = self.gamma1, self.gamma2, self.epsilon
        cw = self.clip_weights if self.clip_weights else -1.0
        centered = self.centered

        def rule(w, g, s, lr, wd):
            import jax.numpy as jnp
            g = self._pure_prep_grad(g, w, wd)
            if not centered:
                (n,) = s
                n_t = (1 - g1) * jnp.square(g) + g1 * n
                w_t = w - lr * g / jnp.sqrt(n_t + eps)
                if cw > 0:
                    w_t = jnp.clip(w_t, -cw, cw)
                return w_t, (n_t,)
            n, gs, delta = s
            n_t = (1 - g1) * jnp.square(g) + g1 * n
            g_t = (1 - g1) * g + g1 * gs
            d_t = g2 * delta - lr * g / jnp.sqrt(n_t - jnp.square(g_t) + eps)
            w_t = w + d_t
            if cw > 0:
                w_t = jnp.clip(w_t, -cw, cw)
            return w_t, (n_t, g_t, d_t)

        return rule


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._data = (self.rho * acc_g + (1 - self.rho) * g * g)._data
        current_delta = nd.sqrt(acc_delta + self.epsilon) / nd.sqrt(acc_g + self.epsilon) * g
        acc_delta._data = (self.rho * acc_delta + (1 - self.rho) * current_delta * current_delta)._data
        weight._data = (weight - current_delta - wd * weight)._data

    def pure_rule(self):
        rho, eps = self.rho, self.epsilon

        def rule(w, g, s, lr, wd):
            import jax.numpy as jnp
            g = g * self.rescale_grad
            if self.clip_gradient is not None and self.clip_gradient > 0:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            acc_g, acc_d = s
            acc_g_t = rho * acc_g + (1 - rho) * g * g
            cur = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g_t + eps) * g
            acc_d_t = rho * acc_d + (1 - rho) * cur * cur
            return w - cur - wd * w, (acc_g_t, acc_d_t)

        return rule


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        z, n_ = state
        sigma = -nd.sqrt(n_)
        n_._data = (n_ + g * g)._data
        sigma += nd.sqrt(n_)
        sigma /= lr
        z._data = (z + g - sigma * weight)._data
        w_np = z.asnumpy()
        n_np = n_.asnumpy()
        new_w = np.where(
            np.abs(w_np) > self.lamda1,
            -(w_np - np.sign(w_np) * self.lamda1)
            / ((self.beta + np.sqrt(n_np)) / lr + wd),
            0.0,
        ).astype(np.float32)
        weight[:] = new_w


@register
class Test(Optimizer):
    """Simple test optimizer (reference optimizer.py Test)."""

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        weight._data = (weight + grad * self.rescale_grad)._data
        state._data = weight._data


def _state_structure(s):
    """Nested (shape, dtype) signature of an optimizer state tree — used to
    detect when a hyperparameter mutation changed what create_state returns
    (e.g. momentum 0.0 -> 0.9 turns a None state into a buffer)."""
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_structure(x) for x in s)
    return (tuple(s.shape), str(s.dtype))


class Updater:
    """Closure applying an optimizer keyed by integer index — the object the
    reference installs into KVStore (optimizer.py get_updater / :768ff)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self._state_keys = {}
        self._tree_fn = None
        self._tree_keys = None
        self._lw_cache = None

    def ensure_state(self, index, weight, key=None):
        """Create — or structurally refresh — the state for `index`.
        Refresh matters when a hyperparameter mutation changes the state
        create_state would build: raising momentum from 0.0 (state None) to
        nonzero mid-training must materialize a real momentum buffer, or the
        retraced rule silently keeps running momentum-free SGD.
        Callers looping over many params pass the precomputed `key` so the
        sorted-vars walk happens once per step, not once per param. The
        throwaway create_state on a key change is bounded to once per
        hyperparam mutation (or checkpoint restore) per param — rare events."""
        if key is None:
            key = self.optimizer._hyperparam_key()
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        elif self._state_keys.get(index) != key:
            fresh = self.optimizer.create_state(index, weight)
            if _state_structure(fresh) != _state_structure(self.states[index]):
                self.states[index] = fresh
        self._state_keys[index] = key
        return self.states[index]

    def ensure_state_sharded(self, index, weight, mesh, axis_name="data",
                             key=None):
        """ensure_state with the weight viewed in its ZeRO-1 layout, so NEW
        state buffers are BORN 1/N-sharded across the data axis
        (_zeros_like_state inherits the weight's sharding) instead of
        allocated replicated and resharded later. Existing states are
        returned untouched — callers reshard those copies themselves."""
        import jax

        from .parallel.collectives import zero1_sharding

        w = weight._data
        sh = zero1_sharding(mesh, w.shape, axis_name)
        if w.sharding != sh:
            w = jax.device_put(w, sh)
        return self.ensure_state(index, NDArray(w), key=key)

    def __call__(self, index, grad, weight):
        self.ensure_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def update_all(self, pairs):
        """Apply the optimizer to many (index, grad, weight) pairs in ONE
        jitted XLA program (optimizer.pure_rule), instead of one dispatch
        per key — the whole-tree analogue of the reference executing its
        fused optimizer kernels (optimizer_op.cc) under engine bulk
        segments. Falls back to per-key eager updates when the optimizer
        has no pure rule. lr/wd enter as dynamic scalars (no retrace when
        an LR schedule changes them)."""
        import jax
        import jax.numpy as jnp

        rule = self.optimizer.pure_rule()
        if rule is None:
            hyper_key = self.optimizer._hyperparam_key()
            for index, grad, weight in pairs:
                self.ensure_state(index, weight, key=hyper_key)
                self.optimizer.update(index, weight, grad, self.states[index])
            return
        opt = self.optimizer
        hyper_key = opt._hyperparam_key()
        for index, _, weight in pairs:
            self.ensure_state(index, weight, key=hyper_key)
            opt._update_count(index)

        keys = tuple(sorted(p[0] for p in pairs))
        by_idx = {p[0]: p for p in pairs}
        weights = {str(i): by_idx[i][2]._data for i in keys}
        grads = {str(i): by_idx[i][1]._data for i in keys}
        states = {str(i): state_leaves(self.states[i]) for i in keys}
        # lr/wd ship as TWO stacked arrays (one h2d transfer each), not
        # hundreds of scalar buffers; indexed inside the jitted program.
        # Cached across steps: constant-lr training re-uploads nothing.
        lw = np.array([opt.effective_lr_wd(i) for i in keys], np.float32)
        lr_arr, wd_arr, self._lw_cache = cached_lr_wd_arrays(
            self._lw_cache, lw)

        if (self._tree_fn is None or self._tree_keys != keys
                or getattr(self, "_tree_hyper", None) != hyper_key):
            def tree_update(weights, grads, states, lr_arr, wd_arr):
                new_w, new_s = {}, {}
                for pos, i in enumerate(keys):
                    k = str(i)
                    new_w[k], new_s[k] = rule(weights[k], grads[k],
                                              states[k], lr_arr[pos],
                                              wd_arr[pos])
                return new_w, new_s

            # donate only the states: weight buffers can be aliased by
            # user-held NDArrays (set_params / _put fast path), and donation
            # would delete them under the caller
            self._tree_fn = jax.jit(tree_update, donate_argnums=(2,))
            self._tree_keys = keys
            self._tree_hyper = hyper_key

        new_w, new_s = self._tree_fn(weights, grads, states, lr_arr, wd_arr)
        for i in keys:
            k = str(i)
            by_idx[i][2]._data = new_w[k]
            write_state_leaves(self.states[i], new_s[k])

    def set_states(self, states):
        blob = pickle.loads(states)
        counts = blob.pop("__update_counts__", None)
        if counts is not None:
            # restore per-index step counts so bias-corrected optimizers
            # (Adam) continue from the right timestep after resume
            self.optimizer._index_update_count = dict(counts)
            if counts:
                self.optimizer.num_update = max(
                    self.optimizer.num_update, max(counts.values()))
        restored = {}
        for k, v in blob.items():
            if isinstance(v, tuple):
                restored[k] = tuple(None if x is None else nd.array(x) for x in v)
            elif v is None:
                restored[k] = None
            else:
                restored[k] = nd.array(v)
        self.states = restored
        self._state_keys = {}  # restored states re-validate lazily

    def get_states(self):
        def conv(v):
            if v is None:
                return None
            if isinstance(v, tuple):
                return tuple(None if x is None else x.asnumpy() for x in v)
            return v.asnumpy()

        blob = {k: conv(v) for k, v in self.states.items()}
        blob["__update_counts__"] = dict(self.optimizer._index_update_count)
        return pickle.dumps(blob)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
