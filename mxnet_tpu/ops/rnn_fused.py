"""Fused multi-layer RNN operator.

TPU-native equivalent of the reference's cuDNN-only fused `RNN` op
(src/operator/rnn.cc:14 — CPU forward aborts in the reference;
cudnn_rnn-inl.h:22,127-267 wraps cudnnRNNForwardTraining). Here the fused
kernel is a lax.scan over time per layer: the per-step gate matmuls are
single large dot_generals on the MXU, weights stay resident, and XLA
pipelines the scan — the idiomatic TPU counterpart of cuDNN's fused kernels.

Parameter blob layout matches the reference/cuDNN packing (all i2h+h2h
weights layer-major, then all biases) so FusedRNNCell._slice_weights and
unpack_weights round-trip identically.

Layouts: data (T, N, input_size); state (num_layers*dirs, N, H).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import defop, get_op

_NUM_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, mode, bidirectional=False):
    """Total packed parameter count (reference rnn-inl.h GetRnnParamSize)."""
    gates = _NUM_GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        ni = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (ni + state_size)  # weights
    size += num_layers * dirs * gates * state_size * 2  # biases
    return size


def _unpack_params(params, num_layers, input_size, state_size, mode, dirs):
    gates = _NUM_GATES[mode]
    h = state_size
    out = []
    p = 0
    for layer in range(num_layers):
        ni = input_size if layer == 0 else h * dirs
        layer_params = []
        for _ in range(dirs):
            wi = params[p : p + gates * h * ni].reshape(gates * h, ni)
            p += gates * h * ni
            wh = params[p : p + gates * h * h].reshape(gates * h, h)
            p += gates * h * h
            layer_params.append([wi, wh])
        out.append(layer_params)
    for layer in range(num_layers):
        for d in range(dirs):
            bi = params[p : p + gates * h]
            p += gates * h
            bh = params[p : p + gates * h]
            p += gates * h
            out[layer][d].extend([bi, bh])
    return out


def _lstm_scan(x_seq, h0, c0, wi, wh, bi, bh, h):
    """One direction of one LSTM layer: scan over time; gate order i,f,g,o
    (cuDNN order, matching FusedRNNCell._gate_names)."""
    ib = x_seq @ wi.T + (bi + bh)  # (T, N, 4H): hoist input projection out of scan

    from .pallas import lstm as _pl_lstm
    if _pl_lstm.use_for(x_seq.shape[1], h):
        (h_last, c_last), ys = _lstm_scan_fused(ib, h0, c0, wh)
        return ys, h_last, c_last

    def step(carry, xt):
        h_prev, c_prev = carry
        gates = xt + h_prev @ wh.T
        i = jax.nn.sigmoid(gates[:, 0 * h : 1 * h])
        f = jax.nn.sigmoid(gates[:, 1 * h : 2 * h])
        g = jnp.tanh(gates[:, 2 * h : 3 * h])
        o = jax.nn.sigmoid(gates[:, 3 * h : 4 * h])
        c = f * c_prev + i * g
        hh = o * jnp.tanh(c)
        return (hh, c), hh

    (h_last, c_last), ys = jax.lax.scan(step, (h0, c0), ib)
    return ys, h_last, c_last


def _lstm_scan_jnp(ib, h0, c0, wh, h):
    def step(carry, xt):
        h_prev, c_prev = carry
        gates = xt + h_prev @ wh.T
        i = jax.nn.sigmoid(gates[:, 0 * h: 1 * h])
        f = jax.nn.sigmoid(gates[:, 1 * h: 2 * h])
        g = jnp.tanh(gates[:, 2 * h: 3 * h])
        o = jax.nn.sigmoid(gates[:, 3 * h: 4 * h])
        c = f * c_prev + i * g
        hh = o * jnp.tanh(c)
        return (hh, c), hh

    return jax.lax.scan(step, (h0, c0), ib)


@jax.custom_vjp
def _lstm_scan_fused(ib, h0, c0, wh):
    """Scan whose per-step body is the Pallas fused step kernel
    (ops/pallas/lstm.py) — recurrent matmul + gates in one VMEM pass.
    Backward recomputes through the jnp formulation (identical math)."""
    from .pallas import lstm as _pl_lstm

    def step(carry, xt):
        h_prev, c_prev = carry
        hh, c = _pl_lstm.lstm_step(xt, h_prev, c_prev, wh)
        return (hh, c), hh

    return jax.lax.scan(step, (h0, c0), ib)


def _lstm_fused_fwd(ib, h0, c0, wh):
    return _lstm_scan_fused(ib, h0, c0, wh), (ib, h0, c0, wh)


def _lstm_fused_bwd(res, g):
    ib, h0, c0, wh = res
    h = h0.shape[-1]
    _, vjp = jax.vjp(lambda a, b, c, w: _lstm_scan_jnp(a, b, c, w, h),
                     ib, h0, c0, wh)
    return vjp(g)


_lstm_scan_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


def _gru_scan(x_seq, h0, wi, wh, bi, bh, h):
    """GRU scan; gate order r,z,o (cuDNN/reference order)."""
    ib = x_seq @ wi.T + bi  # (T, N, 3H)

    def step(h_prev, xt):
        hb = h_prev @ wh.T + bh
        r = jax.nn.sigmoid(xt[:, 0 * h : 1 * h] + hb[:, 0 * h : 1 * h])
        z = jax.nn.sigmoid(xt[:, 1 * h : 2 * h] + hb[:, 1 * h : 2 * h])
        o = jnp.tanh(xt[:, 2 * h : 3 * h] + r * hb[:, 2 * h : 3 * h])
        hh = (1 - z) * o + z * h_prev
        return hh, hh

    h_last, ys = jax.lax.scan(step, h0, ib)
    return ys, h_last


def _rnn_scan(x_seq, h0, wi, wh, bi, bh, h, act):
    ib = x_seq @ wi.T + (bi + bh)

    def step(h_prev, xt):
        hh = act(xt + h_prev @ wh.T)
        return hh, hh

    h_last, ys = jax.lax.scan(step, h0, ib)
    return ys, h_last


@defop(
    "RNN",
    arg_names=lambda attrs: (
        ("data", "parameters", "state", "state_cell")
        if attrs.get("mode", "lstm") == "lstm"
        else ("data", "parameters", "state")
    ),
    param_spec={
        "state_size": 0,
        "num_layers": 1,
        "bidirectional": False,
        "mode": "lstm",
        "p": 0.0,
        "state_outputs": False,
        "pkeep_": 1.0,
        "lstm_q_": False,
    },
    num_outputs=lambda attrs: (
        1 if not attrs.get("state_outputs")
        else (3 if attrs.get("mode", "lstm") == "lstm" else 2)
    ),
    uses_train=True,
    needs_rng=True,
    simple=False,
)
def _rnn(attrs, inputs, aux, ctx):
    """Fused RNN forward (see module docstring). data: (T,N,I)."""
    mode = attrs["mode"]
    if mode == "lstm":
        data, params, state, state_cell = inputs
    else:
        data, params, state = inputs
        state_cell = None
    h = int(attrs["state_size"])
    num_layers = int(attrs["num_layers"])
    dirs = 2 if attrs["bidirectional"] else 1
    input_size = data.shape[2]
    layer_params = _unpack_params(params, num_layers, input_size, h, mode, dirs)
    dropout = float(attrs["p"])

    x = data
    h_states = []
    c_states = []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            wi, wh, bi, bh = layer_params[layer][d]
            idx = layer * dirs + d
            h0 = state[idx]
            x_dir = x if d == 0 else jnp.flip(x, axis=0)
            if mode == "lstm":
                c0 = state_cell[idx]
                ys, h_last, c_last = _lstm_scan(x_dir, h0, c0, wi, wh, bi, bh, h)
                c_states.append(c_last)
            elif mode == "gru":
                ys, h_last = _gru_scan(x_dir, h0, wi, wh, bi, bh, h)
            else:
                act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
                ys, h_last = _rnn_scan(x_dir, h0, wi, wh, bi, bh, h, act)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_states.append(h_last)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=2)
        if dropout > 0 and ctx.is_train and layer != num_layers - 1:
            keep = 1.0 - dropout
            mask = jax.random.bernoulli(jax.random.fold_in(ctx.rng, layer), keep, x.shape)
            x = x * mask.astype(x.dtype) / keep

    if not attrs["state_outputs"]:
        return (x,), ()
    h_out = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_states, axis=0)
        return (x, h_out, c_out), ()
    return (x, h_out), ()


def _rnn_infer(attrs, shapes):
    """Parameter-blob shape rule for simple_bind."""
    data = shapes[0]
    if data is None:
        return shapes
    size = rnn_param_size(
        int(attrs["num_layers"]), data[2], int(attrs["state_size"]),
        attrs["mode"], bool(attrs["bidirectional"]),
    )
    if shapes[1] is None:
        shapes[1] = (size,)
    dirs = 2 if attrs["bidirectional"] else 1
    state_shape = (int(attrs["num_layers"]) * dirs, data[1], int(attrs["state_size"]))
    for i in range(2, len(shapes)):
        if shapes[i] is None:
            shapes[i] = state_shape
    return shapes


get_op("RNN").infer_params = _rnn_infer
