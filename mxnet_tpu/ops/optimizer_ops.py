"""Fused optimizer update operators.

TPU-native equivalents of src/operator/tensor/optimizer_op.cc (sgd_update,
sgd_mom_update, adam_update, rmsprop_update, rmspropalex_update — SURVEY
§2.1 #17), used by both the Python optimizers and the KVStore updater path.
The reference mutates weight/state in place under engine ordering; here each
op returns the updated tensors and callers rebind (with buffer donation under
jit, XLA updates in place — same memory behaviour, functional API).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import defop


def _prep_grad(grad, weight, attrs):
    g = grad * attrs["rescale_grad"]
    clip = attrs["clip_gradient"]
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g + attrs["wd"] * weight


_COMMON = {"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0}


@defop("sgd_update", arg_names=("weight", "grad"), param_spec=dict(_COMMON))
def _sgd_update(attrs, weight, grad):
    """weight -= lr * (rescale*clip(grad) + wd*weight) (optimizer_op.cc)."""
    return weight - attrs["lr"] * _prep_grad(grad, weight, attrs)


@defop(
    "sgd_mom_update",
    arg_names=("weight", "grad", "mom"),
    param_spec=dict(_COMMON, momentum=0.0),
    num_outputs=2,
)
def _sgd_mom_update(attrs, weight, grad, mom):
    """mom = momentum*mom - lr*g; weight += mom. Returns (weight, mom)."""
    new_mom = attrs["momentum"] * mom - attrs["lr"] * _prep_grad(grad, weight, attrs)
    return weight + new_mom, new_mom


@defop(
    "adam_update",
    arg_names=("weight", "grad", "mean", "var"),
    param_spec=dict(_COMMON, beta1=0.9, beta2=0.999, epsilon=1e-8),
    num_outputs=3,
)
def _adam_update(attrs, weight, grad, mean, var):
    """Adam fused step; returns (weight, mean, var). Bias correction is done
    by the Python Optimizer via the lr schedule, as in the reference."""
    g = grad * attrs["rescale_grad"]
    clip = attrs["clip_gradient"]
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    g = g + attrs["wd"] * weight
    mean_t = attrs["beta1"] * mean + (1 - attrs["beta1"]) * g
    var_t = attrs["beta2"] * var + (1 - attrs["beta2"]) * jnp.square(g)
    w_t = weight - attrs["lr"] * mean_t / (jnp.sqrt(var_t) + attrs["epsilon"])
    return w_t, mean_t, var_t


@defop(
    "rmsprop_update",
    arg_names=("weight", "grad", "n"),
    param_spec=dict(_COMMON, gamma1=0.95, epsilon=1e-8, clip_weights=-1.0),
    num_outputs=2,
)
def _rmsprop_update(attrs, weight, grad, n):
    """RMSProp (Tieleman & Hinton) fused step; returns (weight, n)."""
    g = _prep_grad(grad, weight, attrs)
    n_t = (1 - attrs["gamma1"]) * jnp.square(g) + attrs["gamma1"] * n
    w_t = weight - attrs["lr"] * g / jnp.sqrt(n_t + attrs["epsilon"])
    cw = attrs["clip_weights"]
    if cw is not None and cw > 0:
        w_t = jnp.clip(w_t, -cw, cw)
    return w_t, n_t


@defop(
    "rmspropalex_update",
    arg_names=("weight", "grad", "n", "g", "delta"),
    param_spec=dict(_COMMON, gamma1=0.95, gamma2=0.9, epsilon=1e-8, clip_weights=-1.0),
    num_outputs=4,
)
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    """RMSProp (Graves 2013 variant); returns (weight, n, g, delta)."""
    g = _prep_grad(grad, weight, attrs)
    n_t = (1 - attrs["gamma1"]) * jnp.square(g) + attrs["gamma1"] * n
    g_t = (1 - attrs["gamma1"]) * g + attrs["gamma1"] * g_state
    delta_t = attrs["gamma2"] * delta - attrs["lr"] * g / jnp.sqrt(
        n_t - jnp.square(g_t) + attrs["epsilon"]
    )
    w_t = weight + delta_t
    cw = attrs["clip_weights"]
    if cw is not None and cw > 0:
        w_t = jnp.clip(w_t, -cw, cw)
    return w_t, n_t, g_t, delta_t
