"""Single operator registry — the TPU-native replacement for the reference's
THREE registration generations (SURVEY §2.3: legacy ``OperatorProperty`` via
``MXNET_REGISTER_OP_PROPERTY``, NNVM ``FCompute`` ops, and the deprecated
SimpleOp registry — src/operator/, include/mxnet/op_attr_types.h).

One ``OpDef`` per operator carries everything the reference spread across
attribute maps:

- ``impl``          — a pure JAX function (the FCompute / mshadow kernel);
  autodiff comes from ``jax.vjp`` over the composed graph (the reference's
  nnvm::pass::Gradient, graph_executor.cc:233), so no per-op backward is
  registered unless the op *overrides* the mathematical gradient
  (SoftmaxOutput & friends use ``jax.custom_vjp`` inside ``impl``).
- ``arg_names``     — differentiable inputs (ListArguments).
- ``aux_names``     — mutable non-differentiated state (BN moving stats;
  the reference's ListAuxiliaryStates, operator.h:166-480).
- ``param_spec``    — typed attrs with defaults (DMLC_DECLARE_PARAMETER).
- shape/dtype inference is *derived* via ``jax.eval_shape`` instead of
  hand-written InferShape/InferType.

Both user-facing APIs — imperative ``mxnet_tpu.ndarray`` and symbolic
``mxnet_tpu.symbol`` — are *generated* from this registry at import, exactly
as the reference generates its Python API from the C op registry
(python/mxnet/ndarray.py:28-39, OpWrapperGenerator.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from ..base import MXNetError, coerce_attr

OP_REGISTRY: Dict[str, "OpDef"] = {}

# A required parameter (no default) in a param_spec.
REQUIRED = object()


@dataclasses.dataclass
class OpContext:
    """Per-call execution context (reference OpContext, operator.h:42-62)."""

    is_train: bool = False
    rng: Any = None  # jax PRNG key, present iff opdef.needs_rng


@dataclasses.dataclass
class OpDef:
    name: str
    # full signature: impl(attrs, inputs: tuple, aux: tuple, ctx: OpContext)
    #   -> (outputs: tuple, aux_updates: tuple)
    impl: Callable
    arg_names: Any = ("data",)  # list, or fn(attrs)->list
    aux_names: Any = ()
    num_outputs: Any = 1  # int, or fn(attrs)->int
    param_spec: Optional[Dict[str, Any]] = None  # name -> default / REQUIRED
    needs_rng: bool = False
    uses_train: bool = False
    variadic: bool = False  # takes arbitrary list of inputs (Concat, add_n)
    no_grad_inputs: Sequence[str] = ()  # e.g. labels
    doc: str = ""
    py_name: Optional[str] = None  # name exposed in nd/sym namespaces
    output_names: Any = None  # list or fn(attrs)->list; default [name_output]
    param_docs: Optional[Dict[str, str]] = None  # per-param doc text

    def get_arg_names(self, attrs) -> Tuple[str, ...]:
        a = self.arg_names
        return tuple(a(attrs) if callable(a) else a)

    def get_aux_names(self, attrs) -> Tuple[str, ...]:
        a = self.aux_names
        return tuple(a(attrs) if callable(a) else a)

    def get_num_outputs(self, attrs) -> int:
        n = self.num_outputs
        return n(attrs) if callable(n) else n

    def get_output_names(self, attrs):
        o = self.output_names
        if o is None:
            return ["output"] if self.get_num_outputs(attrs) == 1 else [
                "output%d" % i for i in range(self.get_num_outputs(attrs))
            ]
        return list(o(attrs) if callable(o) else o)

    def parse_attrs(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """Validate & coerce kwargs against param_spec (the DMLC parameter
        string-parse step). Unknown keys raise, like dmlc::Parameter::Init."""
        attrs = {}
        if self.param_spec is None:
            return {k: coerce_attr(v) for k, v in kwargs.items()}
        for key, val in kwargs.items():
            if key not in self.param_spec:
                raise MXNetError(
                    "%s got unknown parameter %r (known: %s)"
                    % (self.name, key, sorted(self.param_spec))
                )
            attrs[key] = coerce_attr(val)
        for key, default in self.param_spec.items():
            if key in attrs:
                continue
            if default is REQUIRED:
                raise MXNetError("%s requires parameter %r" % (self.name, key))
            attrs[key] = default
        return attrs

    def build_doc(self) -> str:
        """Generate the full user-facing docstring from the registry entry
        — summary, tensor inputs, and one entry per parameter with
        type/required-or-default (+ doc text when registered). This is the
        analogue of the reference generating Python docstrings from each
        param struct's __FIELDS__ (src/operator/convolution.cc:158,
        cpp-package/scripts/OpWrapperGenerator.py)."""
        lines = [(self.doc or "%s operator." % self.name).strip(), ""]
        defaults = {k: v for k, v in (self.param_spec or {}).items()
                    if v is not REQUIRED}
        if self.variadic:
            inputs = ["*data : NDArray/Symbol (variable number of inputs)"]
        else:
            try:
                inputs = ["%s : NDArray/Symbol" % n
                          for n in self.get_arg_names(defaults)]
                inputs += ["%s : NDArray/Symbol (auxiliary state)" % n
                           for n in self.get_aux_names(defaults)]
            except Exception:
                inputs = ["data : NDArray/Symbol"]
        lines.append("Inputs")
        lines.append("------")
        lines.extend(inputs)
        if self.param_spec:
            lines.append("")
            lines.append("Parameters")
            lines.append("----------")
            pdocs = self.param_docs or {}
            for key, default in self.param_spec.items():
                if default is REQUIRED:
                    head = "%s : required" % key
                else:
                    tname = type(default).__name__ if default is not None else "any"
                    head = "%s : %s, optional, default=%r" % (key, tname, default)
                lines.append(head)
                if key in pdocs:
                    lines.append("    " + pdocs[key])
        lines.append("")
        lines.append("Returns")
        lines.append("-------")
        n_out = self.num_outputs
        lines.append("%s output(s)" % ("variable" if callable(n_out) else n_out))
        return "\n".join(lines)


def register_op(opdef: OpDef) -> OpDef:
    if opdef.name in OP_REGISTRY:
        raise MXNetError("operator %s already registered" % opdef.name)
    OP_REGISTRY[opdef.name] = opdef
    return opdef


def get_op(name: str) -> OpDef:
    try:
        return OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("unknown operator %r" % name) from None


def defop(
    name: str,
    arg_names=("data",),
    aux_names=(),
    num_outputs=1,
    param_spec=None,
    needs_rng=False,
    uses_train=False,
    variadic=False,
    no_grad_inputs=(),
    py_name=None,
    output_names=None,
    simple=True,
    param_docs=None,
):
    """Decorator registering an operator implementation.

    ``simple=True``  — fn(attrs, *inputs) -> out | tuple(outs)
    ``simple=False`` — fn(attrs, inputs, aux, ctx) -> (outs, aux_updates)
    """

    def dec(fn):
        if simple:

            def impl(attrs, inputs, aux, ctx, _fn=fn):
                out = _fn(attrs, *inputs)
                return (out if isinstance(out, tuple) else (out,)), ()

        else:
            impl = fn
        opdef = OpDef(
            name=name,
            impl=impl,
            arg_names=arg_names,
            aux_names=aux_names,
            num_outputs=num_outputs,
            param_spec=param_spec,
            needs_rng=needs_rng,
            uses_train=uses_train,
            variadic=variadic,
            no_grad_inputs=no_grad_inputs,
            doc=fn.__doc__ or "",
            py_name=py_name or name,
            output_names=output_names,
            param_docs=param_docs,
        )
        register_op(opdef)
        return fn

    return dec


def alias(opdef_name: str, *names: str):
    """Register alternative registry names for an op (reference add_alias)."""
    op = get_op(opdef_name)
    for n in names:
        if n not in OP_REGISTRY:
            OP_REGISTRY[n] = op
