"""Initialization and sampling operators.

TPU-native equivalents of src/operator/tensor/init_op.cc (_zeros/_ones/
_arange/zeros_like/ones_like) and sample_op.cc (uniform/normal with
resource-managed PRNG — here the PRNG is a threaded jax key, SURVEY §2.1 #8).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import defop, alias


def _np_dtype(d):
    if d == "bfloat16":
        return jnp.bfloat16
    return jnp.dtype(np.dtype(d or "float32"))


@defop("_zeros", arg_names=(), param_spec={"shape": (), "ctx": None, "dtype": "float32"})
def _zeros(attrs):
    return jnp.zeros(tuple(attrs["shape"]), _np_dtype(attrs["dtype"]))


@defop("_ones", arg_names=(), param_spec={"shape": (), "ctx": None, "dtype": "float32"})
def _ones(attrs):
    return jnp.ones(tuple(attrs["shape"]), _np_dtype(attrs["dtype"]))


@defop(
    "_full",
    arg_names=(),
    param_spec={"shape": (), "ctx": None, "dtype": "float32", "value": 0.0},
)
def _full(attrs):
    return jnp.full(tuple(attrs["shape"]), attrs["value"], _np_dtype(attrs["dtype"]))


@defop(
    "_arange",
    arg_names=(),
    param_spec={
        "start": 0.0,
        "stop": None,
        "step": 1.0,
        "repeat": 1,
        "ctx": None,
        "dtype": "float32",
    },
)
def _arange(attrs):
    out = jnp.arange(attrs["start"], attrs["stop"], attrs["step"], dtype=_np_dtype(attrs["dtype"]))
    if attrs["repeat"] != 1:
        out = jnp.repeat(out, int(attrs["repeat"]))
    return out


@defop("zeros_like", arg_names=("data",), param_spec={})
def _zeros_like(attrs, data):
    return jnp.zeros_like(data)


@defop("ones_like", arg_names=("data",), param_spec={})
def _ones_like(attrs, data):
    return jnp.ones_like(data)


@defop("_eye", arg_names=(), param_spec={"N": 0, "M": 0, "k": 0, "ctx": None, "dtype": "float32"})
def _eye(attrs):
    n = int(attrs["N"])
    m = int(attrs["M"]) or n
    return jnp.eye(n, m, k=int(attrs["k"]), dtype=_np_dtype(attrs["dtype"]))


# --- sampling (reference sample_op.cc: _random_uniform / _random_normal) ----
@defop(
    "_random_uniform",
    arg_names=(),
    param_spec={"low": 0.0, "high": 1.0, "shape": (), "ctx": None, "dtype": "float32"},
    needs_rng=True,
    simple=False,
)
def _random_uniform(attrs, inputs, aux, ctx):
    out = jax.random.uniform(
        ctx.rng,
        tuple(attrs["shape"]),
        _np_dtype(attrs["dtype"]),
        minval=attrs["low"],
        maxval=attrs["high"],
    )
    return (out,), ()


@defop(
    "_random_normal",
    arg_names=(),
    param_spec={"loc": 0.0, "scale": 1.0, "shape": (), "ctx": None, "dtype": "float32"},
    needs_rng=True,
    simple=False,
)
def _random_normal(attrs, inputs, aux, ctx):
    out = attrs["loc"] + attrs["scale"] * jax.random.normal(
        ctx.rng, tuple(attrs["shape"]), _np_dtype(attrs["dtype"])
    )
    return (out,), ()


alias("_random_uniform", "uniform", "_sample_uniform")
alias("_random_normal", "normal", "_sample_normal")


@defop(
    "_random_gamma",
    arg_names=(),
    param_spec={"alpha": 1.0, "beta": 1.0, "shape": (), "ctx": None, "dtype": "float32"},
    needs_rng=True,
    simple=False,
)
def _random_gamma(attrs, inputs, aux, ctx):
    out = jax.random.gamma(ctx.rng, attrs["alpha"], tuple(attrs["shape"]), _np_dtype(attrs["dtype"]))
    return (out * attrs["beta"],), ()


@defop(
    "_random_exponential",
    arg_names=(),
    param_spec={"lam": 1.0, "shape": (), "ctx": None, "dtype": "float32"},
    needs_rng=True,
    simple=False,
)
def _random_exponential(attrs, inputs, aux, ctx):
    out = jax.random.exponential(ctx.rng, tuple(attrs["shape"]), _np_dtype(attrs["dtype"]))
    return (out / attrs["lam"],), ()
