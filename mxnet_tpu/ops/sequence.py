"""Sequence operators + binary loss.

TPU-native equivalents of src/operator/sequence_{mask,last,reverse}.cc and
src/operator/tensor/loss_binary_op.cc (softmax_cross_entropy). Layout
follows the reference: time-major (max_len, batch, ...) unless axis says
otherwise; sequence_length is a (batch,) vector of valid lengths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import defop


def _len_mask(seq_len, max_len, batch, dtype):
    steps = jnp.arange(max_len, dtype=jnp.float32).reshape(max_len, 1)
    return (steps < seq_len.astype(jnp.float32).reshape(1, batch)).astype(dtype)


@defop(
    "SequenceMask",
    arg_names=lambda attrs: ("data", "sequence_length") if attrs.get("use_sequence_length") else ("data",),
    param_spec={"use_sequence_length": False, "value": 0.0, "axis": 0},
    no_grad_inputs=("sequence_length",),
)
def _sequence_mask(attrs, data, sequence_length=None):
    """Mask positions past each sequence's length with `value`
    (reference sequence_mask-inl.h)."""
    if sequence_length is None:
        return data
    ax = int(attrs["axis"])
    x = jnp.moveaxis(data, ax, 0) if ax != 0 else data
    t, b = x.shape[0], x.shape[1]
    mask = _len_mask(sequence_length, t, b, x.dtype).reshape((t, b) + (1,) * (x.ndim - 2))
    out = x * mask + attrs["value"] * (1 - mask)
    return jnp.moveaxis(out, 0, ax) if ax != 0 else out


@defop(
    "SequenceLast",
    arg_names=lambda attrs: ("data", "sequence_length") if attrs.get("use_sequence_length") else ("data",),
    param_spec={"use_sequence_length": False, "axis": 0},
    no_grad_inputs=("sequence_length",),
)
def _sequence_last(attrs, data, sequence_length=None):
    """Select the last valid timestep per sequence (reference
    sequence_last-inl.h)."""
    ax = int(attrs["axis"])
    x = jnp.moveaxis(data, ax, 0) if ax != 0 else data
    if sequence_length is None:
        return x[-1]
    idx = jnp.maximum(sequence_length.astype(jnp.int32) - 1, 0)  # (batch,)
    return jax.vmap(lambda col, i: col[i], in_axes=(1, 0))(x, idx)


@defop(
    "SequenceReverse",
    arg_names=lambda attrs: ("data", "sequence_length") if attrs.get("use_sequence_length") else ("data",),
    param_spec={"use_sequence_length": False, "axis": 0},
    no_grad_inputs=("sequence_length",),
)
def _sequence_reverse(attrs, data, sequence_length=None):
    """Reverse the valid prefix of each sequence (reference
    sequence_reverse-inl.h)."""
    if sequence_length is None:
        return jnp.flip(data, axis=0)
    t = data.shape[0]
    steps = jnp.arange(t)

    def rev_one(col, length):  # col: (t, ...), length: scalar
        src = jnp.where(steps < length, length - 1 - steps, steps)
        return col[src]

    return jax.vmap(rev_one, in_axes=(1, 0), out_axes=1)(
        data, sequence_length.astype(jnp.int32)
    )


@defop(
    "softmax_cross_entropy",
    arg_names=("data", "label"),
    param_spec={},
    no_grad_inputs=("label",),
)
def _softmax_cross_entropy(attrs, data, label):
    """Scalar summed cross-entropy (reference loss_binary_op.cc)."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32).reshape(-1, 1), axis=1)
    return -jnp.sum(picked)
