"""The ``Custom`` operator — graph-side entry for Python custom ops.

Registers ``Custom`` in the op registry, dispatching to user classes
registered with ``mxnet_tpu.operator.register`` (reference
src/operator/custom/custom.cc `_Custom` registration + the `_Native` /
`_NDArray` legacy callback ops, SURVEY §2.1 #20). arg/aux/output names are
resolved dynamically by instantiating the user's CustomOpProp — the same
flow as CustomOpProp::ListArguments through the C callback table.
"""
from __future__ import annotations

from .registry import OpDef, register_op


def _prop(attrs):
    from .. import operator as _operator

    return _operator.make_prop(attrs)


def _impl(attrs, inputs, aux, ctx):
    from .. import operator as _operator

    return _operator.apply_custom(attrs, inputs, aux, ctx.is_train)


register_op(
    OpDef(
        name="Custom",
        impl=_impl,
        arg_names=lambda attrs: tuple(_prop(attrs).list_arguments()),
        aux_names=lambda attrs: tuple(_prop(attrs).list_auxiliary_states()),
        num_outputs=lambda attrs: len(_prop(attrs).list_outputs()),
        output_names=lambda attrs: list(_prop(attrs).list_outputs()),
        param_spec=None,  # op_type + free-form kwargs for the prop ctor
        uses_train=True,
        doc=_impl.__doc__ or "Apply a registered Python custom operator.",
        py_name="Custom",
    )
)
