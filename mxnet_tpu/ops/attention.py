"""Attention & modern-normalization operators.

These extend the reference's op set (which predates attention) to cover the
long-context capability goal (SURVEY §5.7): the framework's idiomatic
replacement for unrolled-RNN sequence handling is transformer attention,
sharded over the mesh by the parallel layer (ring attention /
sequence parallelism in mxnet_tpu.parallel).

``MultiHeadAttention`` is the fusion seam: the default impl is XLA-fused
jnp einsum math; when running on TPU with suitable shapes the executor can
swap in the Pallas flash-attention kernel (ops/pallas/flash_attention.py) —
the same layering as the reference's cuDNN fast paths over mshadow
reference impls (src/operator/cudnn_*.h, SURVEY §2.1 #16).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import defop


@defop(
    "LayerNorm",
    arg_names=("data", "gamma", "beta"),
    param_spec={"axis": -1, "eps": 1e-5},
)
def _layer_norm(attrs, data, gamma, beta):
    """Layer normalization over ``axis`` (modern analogue of the reference's
    InstanceNorm/L2Normalization family, src/operator/instance_norm-inl.h)."""
    ax = int(attrs["axis"]) % data.ndim
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = (data - mean) * jax.lax.rsqrt(var + attrs["eps"])
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@defop(
    "RMSNorm",
    arg_names=("data", "gamma"),
    param_spec={"axis": -1, "eps": 1e-6},
)
def _rms_norm(attrs, data, gamma):
    """Root-mean-square norm (no centering) — the bandwidth-cheaper norm
    preferred on TPU (one fewer HBM pass than LayerNorm)."""
    ax = int(attrs["axis"]) % data.ndim
    ms = jnp.mean(jnp.square(data), axis=ax, keepdims=True)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    return data * jax.lax.rsqrt(ms + attrs["eps"]) * gamma.reshape(bshape)


def rope(x, positions=None, base=10000.0):
    """Rotary position embedding over the last axis of (..., T, D)."""
    d = x.shape[-1]
    half = d // 2
    if positions is None:
        positions = jnp.arange(x.shape[-2])
    freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., :, None].astype(jnp.float32) * freq  # (T, half)
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = x[..., :half], x[..., half:]
    sin = sin.astype(x.dtype)
    cos = cos.astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def dot_product_attention(q, k, v, causal=False, scale=None, mask=None):
    """Reference attention math on (B, H, T, D) tensors.

    Computed in float32 accumulation regardless of input dtype (MXU-friendly:
    bf16 inputs, f32 softmax), matching flash-kernel numerics.
    """
    if scale is None:
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        idx_q = jnp.arange(tq)[:, None] + (tk - tq)  # support kv longer than q
        cmask = idx_q >= jnp.arange(tk)[None, :]
        logits = jnp.where(cmask, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


@defop(
    "MultiHeadAttention",
    arg_names=("query", "key", "value"),
    param_spec={"num_heads": 1, "num_kv_heads": 0, "causal": False,
                "use_rope": False, "use_flash": True},
)
def _multi_head_attention(attrs, query, key, value):
    """Fused multi-head attention on (B, T, H*D) projected inputs.

    Splits heads, optionally applies RoPE, runs (flash) attention, and
    merges heads. Projections (in/out) live outside this op as
    FullyConnected so tensor-parallel sharding of the head axis is a pure
    data layout (mxnet_tpu.parallel.tensor_parallel).

    ``num_kv_heads`` < num_heads gives grouped-query attention (GQA;
    =1 is multi-query): key/value carry (B, T, num_kv_heads*D) and each
    kv head serves num_heads/num_kv_heads query heads. Both paths keep
    kv at hkv heads end to end — the flash kernel grids query-head
    groups over the VMEM-resident kv block, the XLA path uses a grouped
    einsum — so KV HBM bandwidth shrinks by h/hkv along with the
    projection params/FLOPs. 0 (default) = standard MHA.
    """
    h = int(attrs["num_heads"])
    hkv = int(attrs["num_kv_heads"]) or h
    if h % hkv:
        raise ValueError("num_heads %d not divisible by num_kv_heads %d"
                         % (h, hkv))
    b, tq, dm = query.shape
    tk = key.shape[1]
    d = dm // h
    causal = bool(attrs["causal"])

    def split(x, t, heads):
        return x.reshape(b, t, heads, d).transpose(0, 2, 1, 3)

    q = split(query, tq, h)
    k, v = split(key, tk, hkv), split(value, tk, hkv)
    if attrs["use_rope"]:
        q, k = rope(q), rope(k)
    if attrs["use_flash"]:
        # flash_attention owns the selection gate (on-TPU + block
        # contract + MIN_SEQ) and takes narrow (B, Hkv, Tk, D) k/v
        # directly — off the fast path it falls back to the grouped
        # einsum / reference math itself, so the predicate lives in ONE
        # place and the two layers cannot drift
        from .pallas import flash_attention as _fa
        out = _fa.flash_attention(q, k, v, causal=causal)
    elif hkv != h:
        out = _grouped_attention(q, k, v, hkv, causal)
    else:
        out = dot_product_attention(q, k, v, causal=causal)
    return out.transpose(0, 2, 1, 3).reshape(b, tq, dm)


def _grouped_attention(q, k, v, hkv, causal, scale=None, mask=None):
    """GQA without materializing repeated kv: q (B, H, Tq, D) grouped as
    (B, Hkv, G, Tq, D) against k/v (B, Hkv, Tk, D) — kv streams once per
    GROUP, which is the bandwidth/KV-cache saving GQA exists for.
    ``mask``: optional (B, Tk) bool of valid key positions (broadcast over
    heads/groups/query) — the KV-cache decode path's per-row length mask."""
    b, hh, tq, d = q.shape
    g = hh // hkv
    q5 = q.reshape(b, hkv, g, tq, d)
    if scale is None:
        scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bkgqd,bkld->bkgql", q5, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tk = logits.shape[-1]
        idx_q = jnp.arange(tq)[:, None] + (tk - tq)
        cmask = idx_q >= jnp.arange(tk)[None, :]
        logits = jnp.where(cmask, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, None, :], logits,
                           jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgql,bkld->bkgqd", probs.astype(v.dtype), v)
    return out.reshape(b, hh, tq, d)


def dequantize_kv(cache, scale):
    """Widen an int8 KV cache view back to f32 for the attention einsum.

    ``cache``: (..., Hkv, C, Dh) int8, ``scale``: (..., C) f32 — one
    scale per cached position, shared across kv heads and head dim (each
    position is written exactly once, so its scale never needs
    requantization). The multiply fuses into the einsum's operand read;
    the HBM-resident slab stays at 1/4 of f32 bytes, which is the whole
    point (docs/deployment.md "Quantized serving").
    """
    return cache.astype(jnp.float32) * scale[..., None, :, None]


def cached_attention(q, k_cache, v_cache, lengths, k_scale=None,
                     v_scale=None):
    """One autoregressive decode step against a padded KV cache.

    ``q``: (B, H, 1, D) — the new token's query (already roped at its
    absolute position). ``k_cache``/``v_cache``: (B, Hkv, C, D) slot
    rows of a KV slab at fixed capacity C, holding each row's keys/values
    at positions [0, lengths[i]] (the new token's k/v already written).
    ``lengths``: (B,) int — the new token's position per row; key slots
    beyond it are masked to exactly zero probability, so a row's output
    is bitwise independent of whatever stale kv other slots or positions
    hold — the invariant continuous batching rests on.

    This is the fixed-shape twin of the prefill-side flash/GQA attention
    (``_multi_head_attention``): same grouped-einsum math, f32 softmax,
    Tq=1. The flash kernel's block contract needs Tq >= block, so the
    decode step stays on the einsum path by construction.

    Low-precision caches (``MXNET_DECODE_KV_DTYPE``): bf16 caches flow
    through the f32-accumulating einsum unchanged; int8 caches carry
    per-position ``k_scale``/``v_scale`` (..., C) and are widened via
    :func:`dequantize_kv` at the einsum input.
    """
    if k_scale is not None:
        k_cache = dequantize_kv(k_cache, k_scale)
        v_cache = dequantize_kv(v_cache, v_scale)
    hkv = k_cache.shape[1]
    cap = k_cache.shape[2]
    mask = jnp.arange(cap)[None, :] <= lengths[:, None]  # (B, C)
    return _grouped_attention(q, k_cache, v_cache, hkv, causal=False,
                              mask=mask)


def prefix_cached_attention(q, k_ctx, v_ctx, ctx_len, k_new, v_new,
                            k_scale=None, v_scale=None):
    """Chunked prefill against a cached prefix (the paged-KV admit path).

    ``q``: (B, H, Tq, D) — queries for ``Tq`` new suffix tokens (already
    roped at absolute positions ``ctx_len + j``). ``k_ctx``/``v_ctx``:
    (B, Hkv, C, D) — the cached prefix at fixed capacity C, valid in
    positions ``[0, ctx_len)``; everything at/after ``ctx_len`` is masked
    to exactly zero probability. ``k_new``/``v_new``: (B, Hkv, Tq, D) —
    the suffix's own keys/values, attended causally (suffix token i sees
    suffix keys 0..i).

    Same grouped-einsum math and f32 softmax as ``cached_attention`` —
    masked lanes contribute exactly 0.0 to the softmax sum, so with
    ``ctx_len == 0`` the result equals plain causal self-attention over
    the suffix, and a shared cached prefix yields the same output as
    recomputing that prefix in-band.

    int8 cached prefixes carry per-position ``k_scale``/``v_scale``
    (..., C), widened at the einsum input like ``cached_attention``;
    ``k_new``/``v_new`` are always full precision (they were just
    computed in-register).
    """
    if k_scale is not None:
        k_ctx = dequantize_kv(k_ctx, k_scale)
        v_ctx = dequantize_kv(v_ctx, v_scale)
    k_new = k_new.astype(k_ctx.dtype)
    v_new = v_new.astype(v_ctx.dtype)
    hkv = k_ctx.shape[1]
    cap = k_ctx.shape[2]
    tq = q.shape[2]
    k_all = jnp.concatenate([k_ctx, k_new], axis=2)
    v_all = jnp.concatenate([v_ctx, v_new], axis=2)
    # ctx keys valid below ctx_len; suffix keys gated by the causal term
    # inside _grouped_attention (idx_q = i + cap admits all ctx keys and
    # exactly the causal suffix prefix).
    ctx_valid = jnp.arange(cap)[None, :] < ctx_len
    suf_valid = jnp.ones((1, tq), bool)
    mask = jnp.concatenate(
        [jnp.broadcast_to(ctx_valid, (q.shape[0], cap)),
         jnp.broadcast_to(suf_valid, (q.shape[0], tq))], axis=1)
    return _grouped_attention(q, k_all, v_all, hkv, causal=True, mask=mask)
