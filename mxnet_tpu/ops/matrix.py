"""Matrix / shape-manipulation / indexing / ordering operators.

TPU-native equivalents of src/operator/tensor/{matrix_op,dot,indexing_op,
ordering_op}.{cc,h} (SURVEY §2.1 #17). All static-shape by construction so
XLA can tile them onto the MXU/VPU; `dot` maps to lax.dot_general which is
the MXU primitive.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import defop, alias


@defop("dot", arg_names=("lhs", "rhs"), param_spec={"transpose_a": False, "transpose_b": False})
def _dot(attrs, lhs, rhs):
    """Matrix product (reference: src/operator/tensor/dot.cc). For ndim>2 the
    reference contracts the last axis of lhs with the first of rhs; matmuls
    land on the MXU via lax.dot_general."""
    if attrs["transpose_a"]:
        lhs = jnp.moveaxis(lhs, 0, -1) if lhs.ndim > 2 else lhs.T
    if attrs["transpose_b"]:
        rhs = jnp.moveaxis(rhs, -1, 0) if rhs.ndim > 2 else rhs.T
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@defop(
    "batch_dot",
    arg_names=("lhs", "rhs"),
    param_spec={"transpose_a": False, "transpose_b": False},
)
def _batch_dot(attrs, lhs, rhs):
    """Batched matmul over leading axis (reference dot.cc batch_dot)."""
    if attrs["transpose_a"]:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if attrs["transpose_b"]:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


def quantized_matmul(x, w, scale, act_dtype="int8"):
    """``x @ dequant(w).T`` with the dequantization fused into the GEMM.

    ``w``: (O, I) int8 or fp8-e4m3 per-channel-quantized weight,
    ``scale``: (O,) or (O, 1) f32 output-channel scales. Two execution
    strategies, picked by ``act_dtype``:

    - ``"int8"`` (int8 weights only): dynamic per-row symmetric
      activation quantization, then a native int8×int8 ``dot_general``
      with i32 accumulation — the MXU's double-rate int8 path (and the
      measured fast path on CPU VNNI); the two scales rescale the i32
      accumulator back to f32.
    - ``"bf16"`` / ``"float32"``: dequant-on-load — the weight is widened
      and scaled right at the GEMM input so XLA fuses the multiply into
      the matmul read; weight bytes in HBM stay 1/4 (or 1/2) of f32.
      fp8 weights always take this path.

    Returns f32, shape ``x.shape[:-1] + (O,)``.
    """
    scale = scale.reshape(-1)                     # (O,)
    out_shape = x.shape[:-1] + (w.shape[0],)
    x2 = x.reshape(-1, x.shape[-1])
    if w.dtype == jnp.int8 and act_dtype == "int8":
        amax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
        xs = jnp.maximum(amax, 1e-12).astype(jnp.float32) / 127.0
        xq = jnp.round(x2 / xs).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * xs * scale[None, :]
    else:
        ct = jnp.bfloat16 if act_dtype == "bf16" else jnp.float32
        wf = w.astype(ct) * scale[:, None].astype(ct)
        out = jax.lax.dot_general(
            x2.astype(ct), wf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    return out.reshape(out_shape)


@defop("transpose", arg_names=("data",), param_spec={"axes": ()})
def _transpose(attrs, data):
    axes = tuple(attrs["axes"]) or None
    return jnp.transpose(data, axes)


@defop("SwapAxis", arg_names=("data",), param_spec={"dim1": 0, "dim2": 0})
def _swapaxis(attrs, data):
    """Swap two axes (reference src/operator/swapaxis.cc)."""
    return jnp.swapaxes(data, int(attrs["dim1"]), int(attrs["dim2"]))


alias("SwapAxis", "swapaxes")


def _infer_reshape(data_shape, target):
    """Reference reshape semantics incl. special codes 0,-1,-2,-3,-4
    (src/operator/tensor/matrix_op.cc ReshapeShape)."""
    out = []
    src = list(data_shape)
    i = 0  # index into src
    t = list(target)
    j = 0
    while j < len(t):
        k = t[j]
        if k == 0:
            out.append(src[i]); i += 1
        elif k == -1:
            out.append(-1); i = min(i + 1, len(src))  # placeholder
        elif k == -2:
            out.extend(src[i:]); i = len(src)
        elif k == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif k == -4:
            a, b = t[j + 1], t[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            # an explicit dim consumes one source dim too (reference
            # ReshapeInferShape ++src_idx on positive dims) — without
            # this, a following -4/-3/0 splits the WRONG source dim
            out.append(int(k)); i = min(i + 1, len(src))
        j += 1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(data_shape)) if data_shape else 1
        out[out.index(-1)] = total // known
    return tuple(out)


@defop("Reshape", arg_names=("data",), param_spec={"shape": (), "reverse": False, "target_shape": (), "keep_highest": False})
def _reshape(attrs, data):
    """Reshape with the reference's 0/-1/-2/-3/-4 codes (matrix_op.cc).
    ``reverse=True`` matches the special codes from the RIGHT (reference
    ReshapeInferShape reverses src dims and target, then un-reverses)."""
    shape = tuple(attrs["shape"]) if attrs["shape"] else tuple(attrs["target_shape"])
    if attrs.get("reverse"):
        inferred = _infer_reshape(data.shape[::-1], shape[::-1])[::-1]
    else:
        inferred = _infer_reshape(data.shape, shape)
    return jnp.reshape(data, inferred)


alias("Reshape", "reshape")


@defop("Flatten", arg_names=("data",), param_spec={})
def _flatten(attrs, data):
    """Collapse all but the leading axis (reference matrix_op.cc Flatten)."""
    return jnp.reshape(data, (data.shape[0], -1))


alias("Flatten", "flatten")


@defop("expand_dims", arg_names=("data",), param_spec={"axis": 0})
def _expand_dims(attrs, data):
    return jnp.expand_dims(data, int(attrs["axis"]))


@defop("slice", arg_names=("data",), param_spec={"begin": (), "end": ()})
def _slice(attrs, data):
    """Static slice (reference matrix_op.cc slice / crop)."""
    begin, end = attrs["begin"], attrs["end"]
    idx = tuple(
        slice(None if b is None else int(b), None if e is None else int(e))
        for b, e in zip(begin, end)
    )
    return data[idx]


alias("slice", "crop")


@defop("slice_axis", arg_names=("data",), param_spec={"axis": 0, "begin": 0, "end": None})
def _slice_axis(attrs, data):
    ax = int(attrs["axis"]) % data.ndim
    begin = int(attrs["begin"])
    end = attrs["end"]
    end = data.shape[ax] if end is None else int(end)
    if end < 0:
        end += data.shape[ax]
    idx = [slice(None)] * data.ndim
    idx[ax] = slice(begin, end)
    return data[tuple(idx)]


@defop(
    "Concat",
    arg_names=(),
    variadic=True,
    param_spec={"num_args": 0, "dim": 1},
    py_name="concat",
)
def _concat(attrs, *inputs):
    """Concatenate along an axis (reference src/operator/concat.cc)."""
    return jnp.concatenate(inputs, axis=int(attrs["dim"]))


alias("Concat", "concat")


@defop(
    "SliceChannel",
    arg_names=("data",),
    param_spec={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
    num_outputs=lambda attrs: int(attrs["num_outputs"]),
    py_name="split",
)
def _slice_channel(attrs, data):
    """Split along an axis into num_outputs parts (reference
    src/operator/slice_channel.cc)."""
    n = int(attrs["num_outputs"])
    ax = int(attrs["axis"])
    parts = jnp.split(data, n, axis=ax)
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=ax) for p in parts]
    return tuple(parts)


alias("SliceChannel", "split")


@defop("repeat", arg_names=("data",), param_spec={"repeats": 1, "axis": None})
def _repeat(attrs, data):
    ax = attrs["axis"]
    return jnp.repeat(data, int(attrs["repeats"]), axis=None if ax is None else int(ax))


@defop("tile", arg_names=("data",), param_spec={"reps": ()})
def _tile(attrs, data):
    return jnp.tile(data, tuple(attrs["reps"]))


@defop("reverse", arg_names=("data",), param_spec={"axis": ()})
def _reverse(attrs, data):
    axes = attrs["axis"]
    if isinstance(axes, (int, np.integer)):
        axes = (axes,)
    return jnp.flip(data, axis=tuple(int(a) for a in axes))


alias("reverse", "flip")


@defop(
    "Pad",
    arg_names=("data",),
    param_spec={"mode": "constant", "pad_width": (), "constant_value": 0.0},
)
def _pad(attrs, data):
    """N-d padding, constant/edge/reflect (reference src/operator/pad.cc)."""
    pw = attrs["pad_width"]
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2)]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(data, pairs, constant_values=attrs["constant_value"])
    return jnp.pad(data, pairs, mode="edge" if mode == "edge" else "reflect")


alias("Pad", "pad")


# --- indexing (reference indexing_op.cc) ------------------------------------
@defop(
    "Embedding",
    arg_names=("data", "weight"),
    param_spec={"input_dim": 0, "output_dim": 0, "dtype": "float32"},
    no_grad_inputs=("data",),
)
def _embedding(attrs, data, weight):
    """Table lookup; backward is a scatter-add handled by jax.vjp of take
    (reference indexing_op.cc Embedding + EmbeddingOpBackward)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@defop("take", arg_names=("a", "indices"), param_spec={"axis": 0, "mode": "clip"}, no_grad_inputs=("indices",))
def _take(attrs, a, indices):
    mode = attrs["mode"]
    return jnp.take(a, indices.astype(jnp.int32), axis=int(attrs["axis"]),
                    mode="wrap" if mode == "wrap" else "clip")


@defop("batch_take", arg_names=("a", "indices"), param_spec={}, no_grad_inputs=("indices",))
def _batch_take(attrs, a, indices):
    """Per-row gather: out[i] = a[i, indices[i]] (reference batch_take)."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32).reshape(-1, 1), axis=1
    ).reshape(indices.shape)


@defop(
    "one_hot",
    arg_names=("indices",),
    param_spec={"depth": 0, "on_value": 1.0, "off_value": 0.0, "dtype": "float32"},
    no_grad_inputs=("indices",),
)
def _one_hot(attrs, indices):
    depth = int(attrs["depth"])
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(attrs["dtype"]))
    return oh * (attrs["on_value"] - attrs["off_value"]) + attrs["off_value"]


# --- ordering (reference ordering_op.cc) ------------------------------------
@defop("sort", arg_names=("data",), param_spec={"axis": -1, "is_ascend": True})
def _sort(attrs, data):
    ax = attrs["axis"]
    out = jnp.sort(data, axis=None if ax is None else int(ax))
    if not attrs["is_ascend"]:
        out = jnp.flip(out, axis=-1 if ax is None else int(ax))
    return out


@defop("argsort", arg_names=("data",), param_spec={"axis": -1, "is_ascend": True, "dtype": "float32"})
def _argsort(attrs, data):
    ax = attrs["axis"]
    if not attrs["is_ascend"]:
        data = -data
    return jnp.argsort(data, axis=None if ax is None else int(ax)).astype(data.dtype)


@defop(
    "topk",
    arg_names=("data",),
    param_spec={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False, "dtype": "float32"},
    num_outputs=lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1,
)
def _topk(attrs, data):
    """Top-k along an axis (reference ordering_op.cc). ret_typ selects
    value/indices/both/mask."""
    ax = int(attrs["axis"]) % data.ndim
    k = int(attrs["k"])
    moved = jnp.moveaxis(data, ax, -1)
    if attrs["is_ascend"]:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(moved, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idxf = jnp.moveaxis(idx, -1, ax).astype(data.dtype)
    rt = attrs["ret_typ"]
    if rt == "value":
        return vals
    if rt == "both":
        return vals, idxf
    if rt == "mask":
        oh = jax.nn.one_hot(idx, moved.shape[-1], dtype=data.dtype).sum(-2)
        return jnp.moveaxis(oh, -1, ax)
    return idxf
