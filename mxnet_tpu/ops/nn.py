"""Neural-network layer operators.

TPU-native equivalents of the reference's legacy layer ops
(src/operator/*.{cc,cu,-inl.h}: FullyConnected fully_connected-inl.h:76-86,
Convolution convolution-inl.h:90-288, BatchNorm batch_norm-inl.h, Pooling,
Activation, Dropout, LRN, SoftmaxOutput softmax_output-inl.h, ...).

Design notes (TPU-first):
- Convs/matmuls go through lax.conv_general_dilated / dot_general → MXU.
  There is no im2col+gemm staging and no cuDNN algo registry: XLA picks the
  conv algorithm. (The cudnn_* fast-path layer, SURVEY §2.1 #16, is replaced
  by the compiler + optional Pallas kernels registered under the same names.)
- Stateful ops (BatchNorm's moving stats) are functional: impl returns
  (outputs, aux_updates) and the executor threads aux state explicitly.
- Loss "Output" ops replicate the reference's backward semantics exactly via
  jax.custom_vjp (backward injects (prob - label)·scale and ignores the
  incoming head gradient, like softmax_output-inl.h).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import defop, alias


def _ntuple(v, n):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


# --- FullyConnected ---------------------------------------------------------
@defop(
    "FullyConnected",
    arg_names=lambda attrs: ("data", "weight") if attrs.get("no_bias") else ("data", "weight", "bias"),
    param_spec={"num_hidden": 0, "no_bias": False, "flatten": True},
    param_docs={
        "num_hidden": "Number of hidden units (output features).",
        "no_bias": "Whether to disable the bias term.",
        "flatten": "Whether to collapse all but the first axis of the input before the matmul.",
    },
)
def _fully_connected(attrs, data, weight, bias=None):
    """out = dot(data.2d, W.T) + b (reference fully_connected-inl.h:76-86)."""
    if attrs["flatten"]:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    out = jnp.dot(x, weight.T)
    if bias is not None:
        out = out + bias
    return out


# --- QuantizedFullyConnected ------------------------------------------------
@defop(
    "QuantizedFullyConnected",
    arg_names=lambda attrs: (
        ("data", "weight", "scale") if attrs.get("no_bias")
        else ("data", "weight", "scale", "bias")),
    param_spec={"num_hidden": 0, "no_bias": False, "flatten": True,
                "act_dtype": "int8"},
    param_docs={
        "num_hidden": "Number of hidden units (output features).",
        "no_bias": "Whether to disable the bias term.",
        "flatten": "Whether to collapse all but the first axis of the input before the matmul.",
        "act_dtype": "Activation strategy: int8 (dynamic activation quantization, native int8 matmul) | bf16 | float32 (dequant-on-load).",
    },
    no_grad_inputs=("weight", "scale"),
)
def _quantized_fully_connected(attrs, data, weight, scale, bias=None):
    """FullyConnected over a per-channel-quantized int8/fp8 weight
    (weight (O, I), scale (O,) — `mxnet_tpu.quant` PTQ output). Same
    surface as FullyConnected with one extra `scale` input; the matmul
    strategy is `ops.matrix.quantized_matmul`."""
    from .matrix import quantized_matmul

    if attrs["flatten"]:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    out = quantized_matmul(x, weight, scale, attrs["act_dtype"])
    if bias is not None:
        out = out + bias
    return out


# --- Activation -------------------------------------------------------------
@defop("Activation", arg_names=("data",), param_spec={"act_type": "relu"},
       param_docs={"act_type": "Element-wise nonlinearity: relu | sigmoid | tanh | softrelu | softsign | gelu | silu."})
def _activation(attrs, data):
    """relu/sigmoid/tanh/softrelu (reference src/operator/activation.cc)."""
    act = attrs["act_type"]
    if act == "relu":
        return jax.nn.relu(data)
    if act == "sigmoid":
        return jax.nn.sigmoid(data)
    if act == "tanh":
        return jnp.tanh(data)
    if act == "softrelu":
        return jax.nn.softplus(data)
    if act == "softsign":
        return jax.nn.soft_sign(data)
    if act == "gelu":  # beyond-reference: transformer stacks (models/transformer.py)
        return jax.nn.gelu(data)
    if act == "silu" or act == "swish":
        return jax.nn.silu(data)
    raise MXNetError("unknown act_type %r" % act)


@defop(
    "LeakyReLU",
    arg_names=lambda attrs: ("data", "gamma") if attrs.get("act_type") == "prelu" else ("data",),
    param_spec={"act_type": "leaky", "slope": 0.25, "lower_bound": 0.125, "upper_bound": 0.334},
)
def _leaky_relu(attrs, data, gamma=None):
    """leaky/elu/prelu (reference src/operator/leaky_relu-inl.h)."""
    act = attrs["act_type"]
    if act == "leaky":
        return jnp.where(data > 0, data, attrs["slope"] * data)
    if act == "elu":
        return jnp.where(data > 0, data, attrs["slope"] * jnp.expm1(data))
    if act == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act == "rrelu":  # inference behaviour: mean slope
        slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
        return jnp.where(data > 0, data, slope * data)
    raise MXNetError("unknown act_type %r" % act)


# --- Convolution ------------------------------------------------------------
def _conv_dnums(nspatial):
    # NC + spatial for data/out, OI + spatial for kernel (reference layout NCHW/OIHW)
    sp = "".join(chr(ord("0") + i) for i in range(nspatial))  # placeholder
    if nspatial == 1:
        return ("NCH", "OIH", "NCH")
    if nspatial == 2:
        return ("NCHW", "OIHW", "NCHW")
    if nspatial == 3:
        return ("NCDHW", "OIDHW", "NCDHW")
    raise MXNetError("unsupported conv dimensionality %d" % nspatial)


from .registry import REQUIRED

_CONV_SPEC = {
    "kernel": REQUIRED,
    "stride": (),
    "dilate": (),
    "pad": (),
    "num_filter": 0,
    "num_group": 1,
    "workspace": 1024,
    "no_bias": False,
    "cudnn_tune": None,
    "cudnn_off": False,
    "layout": None,
}


def _stem_conv_s2d(data, weight, bias):
    """7x7-stride-2-pad-3 stem conv via 2x2 space-to-depth — numerically
    identical, but the MXU sees 4x the input channels (C=3 pads to 128
    lanes catastrophically; C*4=12 with a 4x4 kernel quadruples the
    contraction utilization). This is the cudnn-fastpath analogue for the
    ImageNet stem (SURVEY §2.1 #16): same registry op, faster lowering.

    out[h] = sum_r w[r] x_pad[2h+r]; splitting r=2q+p turns the stride-2
    8-tap window into a stride-1 4-tap window over 2x2-blocked input:
    out[h] = sum_{q,p} w[2q+p] x_pad[2(h+q)+p].
    """
    N, C, H, W = data.shape
    K = weight.shape[0]
    xp = jnp.pad(data, ((0, 0), (0, 0), (3, 3), (3, 3)))
    hp, wp_ = (H + 6) // 2, (W + 6) // 2
    xs = xp.reshape(N, C, hp, 2, wp_, 2)
    xs = xs.transpose(0, 1, 3, 5, 2, 4).reshape(N, C * 4, hp, wp_)
    wpad = jnp.pad(weight, ((0, 0), (0, 0), (0, 1), (0, 1)))  # 7 -> 8 taps
    ws = wpad.reshape(K, C, 4, 2, 4, 2)
    ws = ws.transpose(0, 1, 3, 5, 2, 4).reshape(K, C * 4, 4, 4)
    dn = jax.lax.conv_dimension_numbers(xs.shape, ws.shape, ("NCHW", "OIHW", "NCHW"))
    out = jax.lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding="VALID", dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, -1, 1, 1))
    return out


def _stem_conv_s2d_nhwc(data, weight, bias):
    """NHWC-resident twin of :func:`_stem_conv_s2d` (same blocked-channel
    index ``(c*2 + hp)*2 + wp``, so the blocked OIHW weight construction
    is shared and only transposed to HWIO at the end)."""
    N, H, W, C = data.shape
    K = weight.shape[0]
    xp = jnp.pad(data, ((0, 0), (3, 3), (3, 3), (0, 0)))
    hp, wp_ = (H + 6) // 2, (W + 6) // 2
    xs = xp.reshape(N, hp, 2, wp_, 2, C)
    xs = xs.transpose(0, 1, 3, 5, 2, 4).reshape(N, hp, wp_, C * 4)
    wpad = jnp.pad(weight, ((0, 0), (0, 0), (0, 1), (0, 1)))  # 7 -> 8 taps
    ws = wpad.reshape(K, C, 4, 2, 4, 2)
    ws = ws.transpose(0, 1, 3, 5, 2, 4).reshape(K, C * 4, 4, 4)
    ws = ws.transpose(2, 3, 1, 0)  # OIHW -> HWIO
    dn = jax.lax.conv_dimension_numbers(xs.shape, ws.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    out = jax.lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding="VALID", dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, 1, 1, -1))
    return out


def _conv_forward(attrs, data, weight, bias):
    kernel = tuple(attrs["kernel"])
    n = len(kernel)
    stride = _ntuple(attrs["stride"], n)
    dilate = _ntuple(attrs["dilate"], n)
    pad = _ntuple(attrs["pad"], n) if attrs["pad"] else (0,) * n
    nhwc = attrs.get("layout") == "NHWC"  # layout-island pass (ops/layout.py)
    c_axis = 3 if nhwc else 1
    sp0 = 1 if nhwc else 2
    if (kernel == (7, 7) and stride == (2, 2) and pad == (3, 3)
            and dilate == (1, 1) and int(attrs["num_group"]) == 1
            and data.ndim == 4 and data.shape[c_axis] <= 4
            and data.shape[0] >= 128  # measured: wins at large batch only
            and data.shape[sp0] % 2 == 0 and data.shape[sp0 + 1] % 2 == 0
            and os.environ.get("MXNET_CONV_S2D", "1") != "0"):
        return (_stem_conv_s2d_nhwc if nhwc else _stem_conv_s2d)(
            data, weight, bias)
    if nhwc:
        # weight stays OIHW at rest (checkpoint/quant/flops parity); the
        # in-program transpose to HWIO is hoisted/fused by XLA and costs
        # one relayout per program, not per step-region
        weight = jnp.transpose(weight, (2, 3, 1, 0))
        dims = ("NHWC", "HWIO", "NHWC")
    else:
        dims = _conv_dnums(n)
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, dims)
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(attrs["num_group"]),
        preferred_element_type=None,
    )
    if bias is not None:
        out = out + (bias.reshape((1, 1, 1, -1)) if nhwc
                     else bias.reshape((1, -1) + (1,) * n))
    return out


_CONV_PARAM_DOCS = {
    "kernel": "Spatial kernel size (h, w) or (d, h, w).",
    "stride": "Window stride per spatial axis; defaults to 1s.",
    "dilate": "Kernel dilation per spatial axis; defaults to 1s.",
    "pad": "Implicit zero padding per spatial axis; defaults to 0s.",
    "num_filter": "Number of output channels.",
    "num_group": "Grouped-convolution group count (input and output channels split into groups).",
    "workspace": "Scratch-space hint in MB; accepted for API parity, XLA plans memory itself.",
    "no_bias": "Whether to disable the bias term.",
    "cudnn_tune": "Accepted for API parity (off|limited_workspace|fastest); algorithm choice is the compiler's.",
    "cudnn_off": "Accepted for API parity; there is no cuDNN on TPU.",
    "layout": "Data layout (NCHW/NCDHW); None means the default NC+spatial. "
              "NHWC is set internally by the layout-island pass "
              "(ops/layout.py, MXNET_CONV_LAYOUT) — data channels-last, "
              "weight still OIHW at the API boundary.",
}


@defop(
    "Convolution",
    arg_names=lambda attrs: ("data", "weight") if attrs.get("no_bias") else ("data", "weight", "bias"),
    param_spec=_CONV_SPEC,
    param_docs=_CONV_PARAM_DOCS,
)
def _convolution(attrs, data, weight, bias=None):
    """N-d convolution, NCHW/OIHW (reference convolution-inl.h:90-288). The
    reference stages im2col+gemm; on TPU lax.conv_general_dilated lowers
    directly onto the MXU."""
    return _conv_forward(attrs, data, weight, bias)


alias("Convolution", "Convolution_v1")


@defop(
    "Deconvolution",
    arg_names=lambda attrs: ("data", "weight") if attrs.get("no_bias", True) else ("data", "weight", "bias"),
    param_spec=dict(_CONV_SPEC, no_bias=True, adj=(), target_shape=()),
    param_docs=dict(_CONV_PARAM_DOCS,
                    adj="Extra output size adjustment per spatial axis (disambiguates stride>1 shapes).",
                    target_shape="Explicit output spatial shape; overrides adj."),
)
def _deconvolution(attrs, data, weight, bias=None):
    """Transposed convolution == gradient of Convolution wrt its input
    (reference deconvolution-inl.h builds it from the conv backward pass; we
    do the same via jax.vjp so shape/padding semantics match exactly:
    out = (in-1)*stride - 2*pad + kernel + adj)."""
    kernel = tuple(attrs["kernel"])
    n = len(kernel)
    stride = _ntuple(attrs["stride"], n)
    pad = _ntuple(attrs["pad"], n) if attrs["pad"] else (0,) * n
    adj = _ntuple(attrs["adj"], n) if attrs["adj"] else (0,) * n
    dilate = _ntuple(attrs["dilate"], n)
    dk = tuple(dilate[i] * (kernel[i] - 1) + 1 for i in range(n))
    if attrs["target_shape"]:
        # target_shape OVERRIDES pad and adj (reference
        # deconvolution-inl.h InferPad: pad = ceil((total - target)/2),
        # adj = (total - target) % 2, so the output lands exactly on
        # target_shape; a user-supplied pad=(99,99)/adj is ignored)
        out_sp = tuple(int(s) for s in attrs["target_shape"])
        diff = tuple((data.shape[2 + i] - 1) * stride[i] + dk[i] - out_sp[i]
                     for i in range(n))
        if any(d < 0 for d in diff):
            raise ValueError(
                "Deconvolution target_shape %s exceeds the maximum "
                "reachable output %s for input %s"
                % (out_sp, tuple((data.shape[2 + i] - 1) * stride[i]
                                 + dk[i] for i in range(n)),
                   data.shape[2:]))
        pad = tuple(max(0, (d + 1) // 2) for d in diff)
    else:
        out_sp = tuple(
            (data.shape[2 + i] - 1) * stride[i]
            - 2 * pad[i]
            + dk[i]
            + adj[i]
            for i in range(n)
        )
    num_filter = int(attrs["num_filter"])
    out_shape = (data.shape[0], num_filter) + out_sp
    conv_attrs = {
        "kernel": kernel,
        "stride": stride,
        "dilate": dilate,
        "pad": pad,
        "num_group": attrs["num_group"],
        "num_filter": data.shape[1],
    }

    def fwd_conv(y):
        return _conv_forward(conv_attrs, y, weight, None)

    # The matching conv's output can exceed the deconv INPUT size when
    # adj rows exist (odd target diff, or explicit adj at any stride):
    # those trailing conv windows carry zero cotangent — pad `data` with
    # trailing zeros so the vjp shapes line up for every reachable
    # output, stride 1 included.
    o_conv = tuple((out_sp[i] + 2 * pad[i] - dk[i]) // stride[i] + 1
                   for i in range(n))
    extra = tuple(o_conv[i] - data.shape[2 + i] for i in range(n))
    if any(e > 0 for e in extra):
        data = jnp.pad(data, ((0, 0), (0, 0))
                       + tuple((0, max(0, e)) for e in extra))
    _, vjp = jax.vjp(fwd_conv, jnp.zeros(out_shape, data.dtype))
    (out,) = vjp(data)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# --- Pooling ----------------------------------------------------------------
@defop(
    "Pooling",
    arg_names=("data",),
    param_spec={
        "kernel": (),
        "pool_type": "max",
        "global_pool": False,
        "stride": (),
        "pad": (),
        "pooling_convention": "valid",
        "cudnn_off": False,
    },
    param_docs={
        "kernel": "Pooling window size per spatial axis.",
        "pool_type": "max | avg | sum.",
        "global_pool": "Pool over the entire spatial extent (kernel ignored).",
        "stride": "Window stride; defaults to 1s.",
        "pad": "Implicit padding; defaults to 0s.",
        "pooling_convention": "Output-shape rounding: valid (floor) or full (ceil, Caffe-compatible).",
        "cudnn_off": "Accepted for API parity; there is no cuDNN on TPU.",
    },
)
def _pooling(attrs, data):
    """max/avg/sum pooling via lax.reduce_window (reference pooling-inl.h,
    src/operator/nn/pool.h). 'full' convention = ceil output sizing.
    ``layout=NHWC`` (set only by the layout-island pass, ops/layout.py)
    runs the same window channels-last."""
    nhwc = attrs.get("layout") == "NHWC"
    nsp = data.ndim - 2
    sp0 = 1 if nhwc else 2  # first spatial axis
    if attrs["global_pool"]:
        axes = tuple(range(sp0, sp0 + nsp))
        if attrs["pool_type"] == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif attrs["pool_type"] == "sum":
            out = jnp.sum(data, axis=axes, keepdims=True)
        else:
            out = jnp.mean(data, axis=axes, keepdims=True)
        return out
    kernel = tuple(attrs["kernel"])
    stride = _ntuple(attrs["stride"], nsp)
    pad = _ntuple(attrs["pad"], nsp) if attrs["pad"] else (0,) * nsp
    pads = []
    for i in range(nsp):
        lo = hi = pad[i]
        if attrs["pooling_convention"] == "full":
            size = data.shape[sp0 + i] + 2 * pad[i] - kernel[i]
            out_i = -(-size // stride[i]) + 1  # ceil
            need = (out_i - 1) * stride[i] + kernel[i] - (data.shape[sp0 + i] + 2 * pad[i])
            hi += max(0, need)
        pads.append((lo, hi))
    if nhwc:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        padcfg = [(0, 0)] + pads + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        padcfg = [(0, 0), (0, 0)] + pads
    ptype = attrs["pool_type"]
    if ptype == "max":
        # init must be a CONCRETE scalar (np, not jnp): reduce_window's
        # autodiff rule needs a known init value to recognize max-pooling
        init = -np.inf if jnp.issubdtype(data.dtype, jnp.floating) else np.iinfo(np.dtype(data.dtype)).min
        return jax.lax.reduce_window(data, np.asarray(init, data.dtype), jax.lax.max, window, strides, padcfg)
    summed = jax.lax.reduce_window(data, np.asarray(0, data.dtype), jax.lax.add, window, strides, padcfg)
    if ptype == "sum":
        return summed
    # avg: reference divides by full kernel size (count includes padding)
    return summed / float(np.prod(kernel))


alias("Pooling", "Pooling_v1")


# --- BatchNorm (stateful: moving_mean / moving_var aux) ---------------------
@defop(
    "BatchNorm",
    arg_names=("data", "gamma", "beta"),
    aux_names=("moving_mean", "moving_var"),
    param_spec={
        "eps": 1e-3,
        "momentum": 0.9,
        "fix_gamma": True,
        "use_global_stats": False,
        "output_mean_var": False,
        "axis": 1,
        "cudnn_off": False,
    },
    param_docs={
        "eps": "Added to variance before rsqrt for numerical stability.",
        "momentum": "Moving-average decay for the running mean/var aux states.",
        "fix_gamma": "Pin gamma to 1 with zero gradient (reference default).",
        "use_global_stats": "Normalize with the moving statistics even in training mode.",
        "output_mean_var": "Also return the batch mean and variance as outputs.",
        "axis": "Channel axis to normalize over.",
        "cudnn_off": "Accepted for API parity; there is no cuDNN on TPU.",
    },
    num_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
    uses_train=True,
    simple=False,
)
def _batch_norm(attrs, inputs, aux, ctx):
    """Batch normalization with moving-average aux state (reference
    batch_norm-inl.h; aux update moving = m*mov + (1-m)*batch). fix_gamma
    (default True, as in the reference) pins gamma to 1 with zero grad."""
    data, gamma, beta = inputs
    moving_mean, moving_var = aux
    ax = int(attrs["axis"]) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    if attrs["fix_gamma"]:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    use_batch = ctx.is_train and not attrs["use_global_stats"]
    if use_batch:
        if data.dtype == jnp.bfloat16:
            # One-pass sufficient statistics: sum(d) and sum(d*d) are
            # sibling reduces over the same operand, which XLA fuses into a
            # SINGLE read of the activation (jnp.var would serialize two
            # passes: mean, then mean((x-mean)^2)). Shifting by the moving
            # mean (a running estimate of the batch mean) conditions the
            # E[d^2]-E[d]^2 subtraction, and the f32 accumulation (the cast
            # fuses into the reduce) carries 24 mantissa bits; bf16-only
            # because f32 inputs with |mean|>>std would still lose to
            # cancellation relative to the two-pass algorithm.
            n = 1
            for i in red:
                n *= data.shape[i]
            shift = jax.lax.stop_gradient(moving_mean.astype(jnp.float32))
            d = data.astype(jnp.float32) - shift.reshape(bshape)
            dmean = jnp.sum(d, axis=red) / n
            var = jnp.maximum(jnp.sum(d * d, axis=red) / n - dmean * dmean, 0.0)
            mean = (shift + dmean).astype(data.dtype)
            var = var.astype(data.dtype)
        else:
            mean = jnp.mean(data, axis=red)
            var = jnp.var(data, axis=red)
        m = attrs["momentum"]
        aux_updates = (
            moving_mean * m + mean * (1 - m),
            moving_var * m + var * (1 - m),
        )
    else:
        mean, var = moving_mean, moving_var
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
        aux_updates = (moving_mean, moving_var)
    inv = jax.lax.rsqrt(var + attrs["eps"])
    out = (data - mean.reshape(bshape)) * inv.reshape(bshape) * gamma.reshape(bshape) + beta.reshape(bshape)
    if attrs["output_mean_var"]:
        return (out, mean, var), aux_updates
    return (out,), aux_updates


alias("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm")


@defop(
    "InstanceNorm",
    arg_names=("data", "gamma", "beta"),
    param_spec={"eps": 1e-3},
)
def _instance_norm(attrs, data, gamma, beta):
    """Per-instance, per-channel normalization (reference instance_norm-inl.h)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + attrs["eps"]) * gamma.reshape(bshape) + beta.reshape(bshape)


@defop(
    "L2Normalization",
    arg_names=("data",),
    param_spec={"eps": 1e-10, "mode": "instance"},
)
def _l2_normalization(attrs, data):
    """L2 normalization, instance/channel/spatial (reference l2_normalization-inl.h)."""
    mode = attrs["mode"]
    if mode == "instance":
        red = tuple(range(1, data.ndim))
    elif mode == "channel":
        red = (1,)
    else:  # spatial
        red = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + attrs["eps"])
    return data / norm


@defop(
    "LRN",
    arg_names=("data",),
    param_spec={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": 5},
)
def _lrn(attrs, data):
    """Cross-channel local response normalization (reference lrn-inl.h).

    The channel-window sum is built from nsize shifted slices instead of a
    generic reduce_window(add): XLA fuses the adds identically, and the
    generic-computation reduce_window has no linearization rule under
    jit(grad(...)) in current jax, which would break the fused
    forward+backward executor path."""
    nsize = int(attrs["nsize"])
    half = nsize // 2
    sq = jnp.square(data)
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sqp = jnp.pad(sq, pad)
    c = data.shape[1]
    acc = sqp[:, 0:c]
    for i in range(1, nsize):
        acc = acc + sqp[:, i:i + c]
    return data * jnp.power(attrs["knorm"] + attrs["alpha"] / nsize * acc, -attrs["beta"])


# --- Dropout ----------------------------------------------------------------
@defop(
    "Dropout",
    arg_names=("data",),
    param_spec={"p": 0.5, "mode": "training"},
    needs_rng=True,
    uses_train=True,
    simple=False,
)
def _dropout(attrs, inputs, aux, ctx):
    """Inverted dropout (reference dropout-inl.h): train: mask/(1-p), eval:
    identity."""
    (data,) = inputs
    p = attrs["p"]
    if not ctx.is_train or p <= 0.0:
        return (data,), ()
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.rng, keep, data.shape)
    return ((data * mask.astype(data.dtype)) / keep,), ()


# --- softmax family ---------------------------------------------------------
@defop("softmax", arg_names=("data",), param_spec={"axis": -1, "temperature": None})
def _softmax(attrs, data):
    """Softmax along an axis (reference src/operator/nn/softmax-inl.h)."""
    t = attrs["temperature"]
    if t:
        data = data / t
    return jax.nn.softmax(data, axis=int(attrs["axis"]))


@defop("log_softmax", arg_names=("data",), param_spec={"axis": -1, "temperature": None})
def _log_softmax(attrs, data):
    t = attrs["temperature"]
    if t:
        data = data / t
    return jax.nn.log_softmax(data, axis=int(attrs["axis"]))


@defop(
    "SoftmaxActivation",
    arg_names=("data",),
    param_spec={"mode": "instance"},
)
def _softmax_activation(attrs, data):
    """Softmax over features (instance) or over channel axis per position
    (reference softmax_activation-inl.h)."""
    if attrs["mode"] == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


_SOFTMAX_OUT_SPEC = {
    "grad_scale": 1.0,
    "ignore_label": -1.0,
    "multi_output": False,
    "use_ignore": False,
    "preserve_shape": False,
    "normalization": "null",
    "out_grad": False,
}


@defop(
    "SoftmaxOutput",
    arg_names=("data", "label"),
    param_spec=_SOFTMAX_OUT_SPEC,
    no_grad_inputs=("label",),
)
def _softmax_output(attrs, data, label):
    """Softmax forward; backward injects (prob - one_hot(label)) * grad_scale,
    ignoring the incoming head gradient — exactly the reference's
    softmax_output-inl.h semantics (including use_ignore and the
    batch/valid/null normalization modes)."""
    multi = attrs["multi_output"]

    def fwd(d):
        if multi:
            return jax.nn.softmax(d, axis=1)
        if attrs["preserve_shape"]:
            return jax.nn.softmax(d, axis=-1)
        return jax.nn.softmax(d.reshape(d.shape[0], -1), axis=-1).reshape(d.shape)

    @jax.custom_vjp
    def op(d, lab):
        return fwd(d)

    def op_fwd(d, lab):
        out = fwd(d)
        return out, (out, lab)

    def op_bwd(res, g):
        out, lab = res
        if multi:
            # data (n, k, x...): label (n, x...) indexes axis 1
            k = out.shape[1]
            oh = jax.nn.one_hot(lab.astype(jnp.int32), k, dtype=out.dtype, axis=1)
        else:
            k = out.shape[-1] if attrs["preserve_shape"] else int(np.prod(out.shape[1:]))
            flat = out.reshape(-1, k)
            oh = jax.nn.one_hot(lab.reshape(-1).astype(jnp.int32), k, dtype=out.dtype).reshape(out.shape)
        grad = out - oh
        scale = attrs["grad_scale"]
        valid = None
        if attrs["use_ignore"]:
            ig = attrs["ignore_label"]
            if multi:
                mask = (lab != ig).astype(out.dtype)
                grad = grad * jnp.expand_dims(mask, 1)
            else:
                mask = (lab != ig).astype(out.dtype).reshape(lab.shape)
                bshape = mask.shape + (1,) * (grad.ndim - mask.ndim)
                grad = grad * mask.reshape(bshape)
            valid = jnp.maximum(mask.sum(), 1.0)
        norm = attrs["normalization"]
        if norm == "batch":
            scale = scale / out.shape[0]
        elif norm == "valid" and valid is not None:
            scale = scale / valid
        grad = grad * scale
        return (grad.astype(out.dtype), jnp.zeros_like(lab))

    op.defvjp(op_fwd, op_bwd)
    return op(data, label)


alias("SoftmaxOutput", "Softmax")


def _regression_output(name, link, grad_fn):
    @defop(
        name,
        arg_names=("data", "label"),
        param_spec={"grad_scale": 1.0},
        no_grad_inputs=("label",),
    )
    def impl(attrs, data, label):
        @jax.custom_vjp
        def op(d, lab):
            return link(d)

        def op_fwd(d, lab):
            out = link(d)
            return out, (out, lab)

        def op_bwd(res, g):
            out, lab = res
            num_out = np.prod(out.shape[1:]) if out.ndim > 1 else 1
            grad = grad_fn(out, lab.reshape(out.shape)) * (attrs["grad_scale"] / num_out)
            return (grad.astype(out.dtype), jnp.zeros_like(lab))

        op.defvjp(op_fwd, op_bwd)
        return op(data, label)

    return impl


# reference: regression_output-inl.h — grads divided by num outputs per sample
_regression_output("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_regression_output("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))
_regression_output(
    "LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l
)


@defop(
    "MakeLoss",
    arg_names=("data",),
    param_spec={"grad_scale": 1.0, "valid_thresh": 0.0, "normalization": "null"},
)
def _make_loss(attrs, data):
    """Custom-loss head: forward identity, backward = grad_scale
    (reference make_loss-inl.h)."""

    @jax.custom_vjp
    def op(d):
        return d

    def op_fwd(d):
        # batch size for normalization="batch"; "valid" needs the data
        # itself (count of entries above valid_thresh, make_loss-inl.h:84)
        batch = d.shape[0] if d.ndim else 1
        res = d if attrs["normalization"] == "valid" else None
        return d, (batch, res)

    def op_bwd(residuals, g):
        batch, d = residuals
        scale = attrs["grad_scale"]
        if attrs["normalization"] == "batch":
            scale = scale / batch
        grad = jnp.full_like(g, scale)
        if attrs["normalization"] == "valid":
            valid = jnp.maximum(
                jnp.sum((d > attrs["valid_thresh"]).astype(g.dtype)), 1.0)
            grad = grad / valid
        return (grad,)

    op.defvjp(op_fwd, op_bwd)
    return op(data)


@defop(
    "SVMOutput",
    arg_names=("data", "label"),
    param_spec={"margin": 1.0, "regularization_coefficient": 1.0, "use_linear": False},
    no_grad_inputs=("label",),
)
def _svm_output(attrs, data, label):
    """Hinge-loss output head (reference svm_output-inl.h): forward identity,
    backward pushes margin violations."""
    margin = attrs["margin"]
    reg = attrs["regularization_coefficient"]

    @jax.custom_vjp
    def op(d, lab):
        return d

    def op_fwd(d, lab):
        return d, (d, lab)

    def op_bwd(res, g):
        d, lab = res
        k = d.shape[1]
        oh = jax.nn.one_hot(lab.astype(jnp.int32), k, dtype=d.dtype)
        score_y = jnp.sum(d * oh, axis=1, keepdims=True)
        if attrs["use_linear"]:
            viol = ((margin - (score_y - d)) > 0).astype(d.dtype) * (1 - oh)
            grad = reg * (viol - oh * viol.sum(axis=1, keepdims=True))
        else:
            dist = margin - (score_y - d)
            viol = jnp.maximum(dist, 0) * (1 - oh)
            grad = 2 * reg * (viol - oh * viol.sum(axis=1, keepdims=True))
        return (grad, jnp.zeros_like(lab))

    op.defvjp(op_fwd, op_bwd)
    return op(data, label)


@defop(
    "UpSampling",
    arg_names=(),
    variadic=True,
    param_spec={"scale": 1, "num_filter": 0, "sample_type": "nearest", "multi_input_mode": "concat", "num_args": 1, "workspace": 512},
)
def _upsampling(attrs, *inputs):
    """Nearest (repeat) or bilinear (deconv-weight) upsampling
    (reference upsampling-inl.h)."""
    scale = int(attrs["scale"])
    if attrs["sample_type"] == "nearest":
        outs = []
        for x in inputs:
            x = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
            outs.append(x)
        if len(outs) == 1:
            return outs[0]
        if attrs["multi_input_mode"] == "sum":
            out = outs[0]
            for o in outs[1:]:
                out = out + o
            return out
        return jnp.concatenate(outs, axis=1)
    # bilinear: inputs = (data, weight); implemented as transposed conv
    data, weight = inputs
    kernel = weight.shape[-1]
    pad = (kernel - scale) // 2 if (kernel - scale) % 2 == 0 else (kernel - scale + 1) // 2
    from .matrix import _dot  # noqa: F401  (keep import graph simple)

    conv_attrs = {
        "kernel": (kernel, kernel),
        "stride": (scale, scale),
        "dilate": (1, 1),
        "pad": (pad, pad),
        "num_group": data.shape[1],
        "num_filter": data.shape[1],
    }
    out_sp = tuple(s * scale for s in data.shape[2:])
    out_shape = (data.shape[0], data.shape[1]) + out_sp

    def fwd_conv(y):
        return _conv_forward(conv_attrs, y, weight, None)

    _, vjp = jax.vjp(fwd_conv, jnp.zeros(out_shape, data.dtype))
    (out,) = vjp(data)
    return out


@defop(
    "GridGenerator",
    arg_names=("data",),
    param_spec={"transform_type": "affine", "target_shape": (0, 0)},
)
def _grid_generator(attrs, data):
    """Affine/warp sampling-grid generation (reference grid_generator-inl.h)."""
    if attrs["transform_type"] == "affine":
        h, w = (int(s) for s in attrs["target_shape"])
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)  # (3, h*w)
        out = jnp.einsum("nij,jk->nik", theta, grid)  # (n, 2, h*w)
        return out.reshape(n, 2, h, w)
    # warp: data is flow field (n, 2, h, w); add identity grid, normalize
    n, _, h, w = data.shape
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    x = (data[:, 0] + gx) * (2.0 / max(w - 1, 1)) - 1
    y = (data[:, 1] + gy) * (2.0 / max(h - 1, 1)) - 1
    return jnp.stack([x, y], axis=1)


def _bilinear_sample(data, grid):
    """Sample data (n,c,h,w) at normalized grid (n,2,oh,ow); zero padding
    outside (shared by BilinearSampler / SpatialTransformer)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        yv = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xv = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yv * w + xv).reshape(n, -1)  # (n, oh*ow)
        out = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        inb = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)).astype(data.dtype)
        return out.reshape(n, c, *gx.shape[1:]) * inb[:, None]

    out = (
        gather(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
        + gather(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
        + gather(y0 + 1, x0) * (wy * (1 - wx))[:, None]
        + gather(y0 + 1, x0 + 1) * (wy * wx)[:, None]
    )
    return out


@defop("BilinearSampler", arg_names=("data", "grid"), param_spec={})
def _bilinear_sampler(attrs, data, grid):
    """Bilinear sampling of data at grid locations (reference
    bilinear_sampler-inl.h)."""
    return _bilinear_sample(data, grid)


@defop(
    "SpatialTransformer",
    arg_names=("data", "loc"),
    param_spec={"target_shape": (0, 0), "transform_type": "affine", "sampler_type": "bilinear", "cudnn_off": False},
)
def _spatial_transformer(attrs, data, loc):
    """Affine spatial transformer = GridGenerator + BilinearSampler
    (reference spatial_transformer-inl.h)."""
    h, w = (int(s) for s in attrs["target_shape"])
    n = data.shape[0]
    theta = loc.reshape(n, 2, 3)
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)
    sample = jnp.einsum("nij,jk->nik", theta, grid).reshape(n, 2, h, w)
    return _bilinear_sample(data, sample)


@defop(
    "ROIPooling",
    arg_names=("data", "rois"),
    param_spec={"pooled_size": (0, 0), "spatial_scale": 1.0},
    no_grad_inputs=("rois",),
)
def _roi_pooling(attrs, data, rois):
    """Max-pool over region proposals (reference roi_pooling-inl.h). rois:
    (n_roi, 5) = [batch_idx, x1, y1, x2, y2]."""
    ph, pw = (int(s) for s in attrs["pooled_size"])
    scale = attrs["spatial_scale"]
    n, c, h, w = data.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[b]  # (c, h, w)
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def pool_cell(i, j):
            ys0 = y1 + (i * rh) // ph
            ys1 = y1 + -((-(i + 1) * rh) // ph)
            xs0 = x1 + (j * rw) // pw
            xs1 = x1 + -((-(j + 1) * rw) // pw)
            mask = ((ys >= ys0) & (ys < jnp.maximum(ys1, ys0 + 1)))[:, None] & (
                (xs >= xs0) & (xs < jnp.maximum(xs1, xs0 + 1))
            )[None, :]
            neg = jnp.asarray(-jnp.inf, data.dtype)
            vals = jnp.where(mask[None], img, neg)
            return jnp.max(vals, axis=(1, 2))

        cells = jnp.stack(
            [jnp.stack([pool_cell(i, j) for j in range(pw)], axis=-1) for i in range(ph)],
            axis=-2,
        )  # (c, ph, pw)
        return jnp.where(jnp.isfinite(cells), cells, 0.0)

    return jax.vmap(one_roi)(rois)


@defop(
    "Crop",
    arg_names=lambda attrs: ("data", "crop_like") if int(attrs.get("num_args", 1)) == 2 else ("data",),
    param_spec={"num_args": 1, "offset": (0, 0), "h_w": (0, 0), "center_crop": False},
    no_grad_inputs=("crop_like",),
)
def _crop(attrs, data, crop_like=None):
    """Crop spatial dims to h_w or to crop_like's size (reference crop-inl.h)."""
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = (int(s) for s in attrs["h_w"])
    if attrs["center_crop"]:
        oy = (data.shape[2] - th) // 2
        ox = (data.shape[3] - tw) // 2
    else:
        oy, ox = (int(s) for s in attrs["offset"])
    return data[:, :, oy : oy + th, ox : ox + tw]


@defop(
    "IdentityAttachKLSparseReg",
    arg_names=("data",),
    param_spec={"sparseness_target": 0.1, "penalty": 0.001, "momentum": 0.9},
    aux_names=("moving_avg",),
    uses_train=True,
    simple=False,
)
def _identity_kl_sparse(attrs, inputs, aux, ctx):
    """Identity with KL sparseness regularizer on backward (reference
    identity_attach_KL_sparse_reg-inl.h)."""
    (data,) = inputs
    (moving,) = aux
    rho = jnp.mean(data, axis=0)
    m = attrs["momentum"]
    new_moving = moving * m + rho * (1 - m) if ctx.is_train else moving
    t = attrs["sparseness_target"]
    pen = attrs["penalty"]

    @jax.custom_vjp
    def op(d):
        return d

    def op_fwd(d):
        return d, jnp.mean(d, axis=0)

    def op_bwd(r, g):
        reg = pen * (-t / jnp.maximum(r, 1e-8) + (1 - t) / jnp.maximum(1 - r, 1e-8))
        return (g + reg[None, :],)

    op.defvjp(op_fwd, op_bwd)
    return (op(data),), (new_moving,)
