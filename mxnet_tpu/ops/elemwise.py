"""Elementwise binary/scalar/unary operators.

TPU-native equivalents of the reference's NNVM-style tensor ops
(src/operator/tensor/elemwise_binary_op_basic.cc:11-80,
elemwise_unary_op.cc, elemwise_binary_scalar_op*.cc, and the ~100 SimpleOp
unary math ops noted at SURVEY §2.1 #17). Gradients come from jax.vjp over
the composed graph, so only the forward kernels are defined; XLA fuses
elementwise chains into surrounding matmuls/convs (no mshadow expression
templates needed — the compiler does that job on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import defop


def _binary(name, fn, py_name=None):
    defop(
        name,
        arg_names=("lhs", "rhs"),
        param_spec={},
        py_name=py_name or name,
    )(lambda attrs, lhs, rhs, _f=fn: _f(lhs, rhs))


def _binary_scalar(name, fn, py_name=None):
    defop(
        name,
        arg_names=("data",),
        param_spec={"scalar": 0.0},
        py_name=py_name or name,
    )(lambda attrs, data, _f=fn: _f(data, jnp.asarray(attrs["scalar"], data.dtype)))


def _unary(name, fn, py_name=None):
    defop(name, arg_names=("data",), param_spec={}, py_name=py_name or name)(
        lambda attrs, data, _f=fn: _f(data)
    )


# --- binary elementwise (reference: elemwise_binary_op_basic.cc) ------------
_binary("elemwise_add", jnp.add, py_name="elemwise_add")
_binary("elemwise_sub", jnp.subtract)
_binary("elemwise_mul", jnp.multiply)
_binary("elemwise_div", jnp.divide)
_binary("_plus", jnp.add)
_binary("_minus", jnp.subtract)
_binary("_mul", jnp.multiply)
_binary("_div", jnp.divide)
_binary("_mod", jnp.mod)
_binary("_power", jnp.power)
_binary("_maximum", jnp.maximum)
_binary("_minimum", jnp.minimum)
_binary("_hypot", jnp.hypot)
# logic ops return same-dtype 0/1 arrays like the reference
_binary("_equal", lambda a, b: (a == b).astype(a.dtype))
_binary("_not_equal", lambda a, b: (a != b).astype(a.dtype))
_binary("_greater", lambda a, b: (a > b).astype(a.dtype))
_binary("_greater_equal", lambda a, b: (a >= b).astype(a.dtype))
_binary("_lesser", lambda a, b: (a < b).astype(a.dtype))
_binary("_lesser_equal", lambda a, b: (a <= b).astype(a.dtype))

# --- binary with scalar (reference: elemwise_binary_scalar_op*.cc) ----------
_binary_scalar("_plus_scalar", jnp.add)
_binary_scalar("_minus_scalar", jnp.subtract)
_binary_scalar("_rminus_scalar", lambda x, s: s - x)
_binary_scalar("_mul_scalar", jnp.multiply)
_binary_scalar("_div_scalar", jnp.divide)
_binary_scalar("_rdiv_scalar", lambda x, s: s / x)
_binary_scalar("_mod_scalar", jnp.mod)
_binary_scalar("_rmod_scalar", lambda x, s: jnp.mod(s, x))
_binary_scalar("_power_scalar", jnp.power)
_binary_scalar("_rpower_scalar", lambda x, s: jnp.power(s, x))
_binary_scalar("_maximum_scalar", jnp.maximum)
_binary_scalar("_minimum_scalar", jnp.minimum)
_binary_scalar("_hypot_scalar", jnp.hypot)
_binary_scalar("_equal_scalar", lambda x, s: (x == s).astype(x.dtype))
_binary_scalar("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype))
_binary_scalar("_greater_scalar", lambda x, s: (x > s).astype(x.dtype))
_binary_scalar("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype))
_binary_scalar("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype))
_binary_scalar("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype))

# --- unary math (reference: elemwise_unary_op.cc + SimpleOp registry) -------
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("rint", jnp.rint)
_unary("round", jnp.round)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)))
_unary("gammaln", lambda x: jax.lax.lgamma(x))
_unary("negative", jnp.negative)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))

_unary("_copy", lambda x: x, py_name="identity")
_unary("stop_gradient", jax.lax.stop_gradient, py_name="stop_gradient")
defop("BlockGrad", arg_names=("data",), param_spec={})(
    lambda attrs, data: jax.lax.stop_gradient(data)
)
defop("make_loss", arg_names=("data",), param_spec={})(lambda attrs, data: data)


@defop("Cast", arg_names=("data",), param_spec={"dtype": "float32"})
def _cast(attrs, data):
    """Cast to a new dtype (reference: src/operator/tensor/elemwise_unary_op.cc
    Cast)."""
    import numpy as np

    return data.astype(jnp.dtype(np.dtype(attrs["dtype"])) if attrs["dtype"] != "bfloat16" else jnp.bfloat16)


@defop("clip", arg_names=("data",), param_spec={"a_min": 0.0, "a_max": 1.0})
def _clip(attrs, data):
    """Clip values to [a_min, a_max] (reference: matrix_op.cc clip)."""
    return jnp.clip(data, attrs["a_min"], attrs["a_max"])


@defop(
    "smooth_l1",
    arg_names=("data",),
    param_spec={"scalar": 1.0},
)
def _smooth_l1(attrs, data):
    """Smooth-L1 (huber) used by detection heads (reference
    elemwise_binary_scalar_op_extended.cc smooth_l1)."""
    s2 = attrs["scalar"] ** 2
    absx = jnp.abs(data)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * jnp.square(data), absx - 0.5 / s2)


# variadic sum (reference ElementWiseSum / add_n, elemwise_sum.cc)
@defop("add_n", arg_names=(), variadic=True, param_spec={"num_args": 0}, py_name="add_n")
def _add_n(attrs, *inputs):
    out = inputs[0]
    for x in inputs[1:]:
        out = out + x
    return out


from .registry import alias  # noqa: E402

alias("add_n", "ElementWiseSum", "_sum")
alias("_copy", "identity")
