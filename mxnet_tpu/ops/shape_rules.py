"""Parameter-shape inference rules for layer ops.

The reference's nnvm InferShape pass propagates shapes bidirectionally so
that `simple_bind` can allocate weights from just the data shape
(SURVEY §3.2, python/mxnet/symbol.py:815 infer_shape). On TPU, *output*
shapes come for free from jax.eval_shape; the only genuinely reverse
inference needed is "given data shape + attrs, what are the parameter/aux
shapes". These per-op rules supply exactly that; everything else needs no
rule.

Each rule: fn(attrs, shapes: list[Optional[tuple]]) -> same-length list with
parameter entries filled in. shapes is ordered arg_names + aux_names.
"""
from __future__ import annotations

import numpy as np

from .registry import get_op


def _prod(xs):
    return int(np.prod(xs)) if len(xs) else 1


def _fc_infer(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    num_hidden = int(attrs["num_hidden"])
    in_dim = _prod(data[1:]) if attrs.get("flatten", True) else data[-1]
    shapes[1] = shapes[1] or (num_hidden, in_dim)
    if not attrs.get("no_bias") and len(shapes) > 2:
        shapes[2] = shapes[2] or (num_hidden,)
    return shapes


def _conv_infer(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    kernel = tuple(int(k) for k in attrs["kernel"])
    nf = int(attrs["num_filter"])
    group = int(attrs.get("num_group", 1))
    shapes[1] = shapes[1] or (nf, data[1] // group) + kernel
    if not attrs.get("no_bias") and len(shapes) > 2:
        shapes[2] = shapes[2] or (nf,)
    return shapes


def _deconv_infer(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    kernel = tuple(int(k) for k in attrs["kernel"])
    nf = int(attrs["num_filter"])
    group = int(attrs.get("num_group", 1))
    shapes[1] = shapes[1] or (data[1], nf // group) + kernel
    if not attrs.get("no_bias", True) and len(shapes) > 2:
        shapes[2] = shapes[2] or (nf,)
    return shapes


def _bn_infer(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    ax = int(attrs.get("axis", 1)) % len(data)
    c = (data[ax],)
    for i in range(1, len(shapes)):
        shapes[i] = shapes[i] or c
    return shapes


def _embedding_infer(attrs, shapes):
    shapes[1] = shapes[1] or (int(attrs["input_dim"]), int(attrs["output_dim"]))
    return shapes


def _prelu_infer(attrs, shapes):
    data = shapes[0]
    if data is None or len(shapes) < 2:
        return shapes
    shapes[1] = shapes[1] or (data[1] if len(data) > 1 else 1,)
    return shapes


def _upsampling_infer(attrs, shapes):
    data = shapes[0]
    if attrs.get("sample_type") == "bilinear" and data is not None and len(shapes) > 1:
        s = int(attrs["scale"])
        k = 2 * s - s % 2
        shapes[1] = shapes[1] or (data[1], 1, k, k)
    return shapes


def _softmax_output_infer(attrs, shapes):
    data = shapes[0]
    if data is None or len(shapes) < 2:
        return shapes
    if attrs.get("multi_output"):
        label = (data[0],) + tuple(data[2:])
    elif attrs.get("preserve_shape"):
        label = tuple(data[:-1])
    else:
        label = (data[0],)
    shapes[1] = shapes[1] or label
    return shapes


def _regression_infer(attrs, shapes):
    if shapes[0] is not None and len(shapes) > 1:
        shapes[1] = shapes[1] or tuple(shapes[0])
    return shapes


def _label_vec_infer(attrs, shapes):
    if shapes[0] is not None and len(shapes) > 1:
        shapes[1] = shapes[1] or (shapes[0][0],)
    return shapes


def install():
    get_op("SoftmaxOutput").infer_params = _softmax_output_infer
    get_op("LinearRegressionOutput").infer_params = _regression_infer
    get_op("MAERegressionOutput").infer_params = _regression_infer
    get_op("LogisticRegressionOutput").infer_params = _regression_infer
    get_op("SVMOutput").infer_params = _label_vec_infer
    get_op("softmax_cross_entropy").infer_params = _label_vec_infer
    get_op("FullyConnected").infer_params = _fc_infer
    get_op("Convolution").infer_params = _conv_infer
    get_op("Deconvolution").infer_params = _deconv_infer
    get_op("BatchNorm").infer_params = _bn_infer
    get_op("InstanceNorm").infer_params = _bn_infer
    get_op("Embedding").infer_params = _embedding_infer
    get_op("LeakyReLU").infer_params = _prelu_infer
    get_op("IdentityAttachKLSparseReg").infer_params = _bn_infer
    get_op("UpSampling").infer_params = _upsampling_infer


install()
