"""Flash attention — Pallas TPU kernel with online softmax.

The fused fast path behind the MultiHeadAttention op (ops/attention.py) and
the building block of ring attention (parallel/ring_attention.py). Never
materializes the (Tq, Tk) score matrix in HBM: a grid cell owns one query
block, streams key/value blocks through VMEM, and keeps the softmax
running-max/running-sum in registers (f32) — the standard
memory-bandwidth-optimal formulation for the MXU.

Falls back to the XLA reference math off-TPU or for non-tile-aligned
shapes, exactly as the reference falls back from cuDNN to the mshadow
kernel (src/operator/convolution.cc cudnn_off path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

BLOCK_Q = 256
BLOCK_K = 256
# Selection gate (the cudnn-autotune "must not lose" contract): measured
# on v5e (examples/transformer/bench_transformer.py micro), the kernel is
# 2.2x at S=2048 and 5.4x at S=4096 but 0.91x at S=512 — short sequences
# amortize the kernel's per-block softmax bookkeeping worse than XLA's
# fused einsum. Gate to sequences where it measurably wins.
MIN_SEQ = 1024
_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale, block_k):
    """One (batch*head, q-block) grid cell."""
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    bq = q.shape[0]
    tk = k_ref.shape[1]
    qi = pl.program_id(1)
    num_k_blocks = pl.cdiv(tk, block_k)

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (BQ, BK)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])                # (BQ, BK)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    init = (jnp.zeros((bq, d), jnp.float32),
            jnp.full((bq,), _NEG_INF, jnp.float32),
            jnp.zeros((bq,), jnp.float32))
    if causal:
        # only blocks at or left of the diagonal contribute
        hi = jax.lax.min(num_k_blocks, pl.cdiv((qi + 1) * bq, block_k))
    else:
        hi = num_k_blocks
    acc, _, l = jax.lax.fori_loop(0, hi, body, init)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _fa_forward(q, k, v, causal, scale, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(BLOCK_Q, tq)
    block_k = min(BLOCK_K, tk)
    kernel = functools.partial(_fa_kernel, causal=causal, scale=scale,
                               block_k=block_k)
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid=(bh, pl.cdiv(tq, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * tq * tk * d,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * tq * tk,
        ),
        interpret=interpret,
        **kwargs,
    )(q, k, v)


def _aligned(t, block):
    return t % min(block, t) == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q3, k3, v3, causal, scale, interpret):
    return _fa_forward(q3, k3, v3, causal, scale, interpret)


def _flash_fwd(q3, k3, v3, causal, scale, interpret):
    return _fa_forward(q3, k3, v3, causal, scale, interpret), (q3, k3, v3)


def _flash_bwd(causal, scale, interpret, res, g):
    # Recompute-based backward through the reference math (the kernel and
    # the reference compute identical values). A blocked Pallas backward is
    # a planned fast path; XLA still fuses this into a handful of matmuls.
    from .. import attention as _att

    q3, k3, v3 = res

    def ref(q, k, v):
        return _att.dot_product_attention(q[:, None], k[:, None], v[:, None],
                                          causal=causal, scale=scale)[:, 0]

    _, vjp = jax.vjp(ref, q3, k3, v3)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """Attention over (B, H, T, D). Pallas on TPU, XLA reference otherwise."""
    from .. import attention as _att
    from . import on_tpu

    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    hard_ok = (_aligned(q.shape[-2], BLOCK_Q)
               and _aligned(k.shape[-2], BLOCK_K)
               and q.shape[-1] % 128 == 0)
    if interpret is None:
        # auto mode: the kernel is SELECTED only on TPU with aligned
        # shapes at sequence lengths where it measurably wins
        if not (on_tpu() and hard_ok and q.shape[-2] >= MIN_SEQ):
            return _att.dot_product_attention(q, k, v, causal=causal,
                                              scale=scale)
        interpret = False
    elif not interpret and not hard_ok:
        # explicit interpret=False forces the compiled kernel PAST the
        # MIN_SEQ perf gate (benches), but shapes Mosaic cannot tile
        # still fall back rather than fail at lowering; interpret=True
        # (tests) runs the interpreter, which handles any shape
        return _att.dot_product_attention(q, k, v, causal=causal,
                                          scale=scale)

    b, h, tq, d = q.shape
    tk = k.shape[2]
    out = _flash(q.reshape(b * h, tq, d), k.reshape(b * h, tk, d),
                 v.reshape(b * h, tk, d), causal, scale, interpret)
    return out.reshape(b, h, tq, d)
