"""Flash attention — Pallas TPU kernel with online softmax.

The fused fast path behind the MultiHeadAttention op (ops/attention.py) and
the building block of ring attention (parallel/ring_attention.py). Never
materializes the (Tq, Tk) score matrix in HBM: a grid cell owns one query
block, streams key/value blocks through VMEM, and keeps the softmax
running-max/running-sum in registers (f32) — the standard
memory-bandwidth-optimal formulation for the MXU.

Two VMEM regimes, selected per shape:

- **resident** (seq <= _RESIDENT_MAX): the whole K/V (or, in the dK/dV
  kernel, Q/dO) sequence sits in VMEM per grid cell and an in-kernel loop
  walks its tiles with the carry in registers. Fastest form — no scratch
  traffic, minimal grid steps — but VMEM scales with sequence length, so
  it hits the 16 MiB scoped-VMEM wall just past 8k at head_dim 128.
- **streaming** (longer): the sequence streams through an extra innermost
  grid dim one ~SUPER_TARGET-sized superblock at a time, the kernel loops
  the superblock's tiles in registers, and the carry lives in VMEM
  scratch across supersteps. Nothing in VMEM scales with total sequence
  length, so 16k/32k+ train in the same footprint as 4k. Measured ~1.5-2x
  slower than resident at seqs where both run (per-superstep scratch
  spill/fill + grid overhead), which is why it only engages where
  resident cannot run at all.

Falls back to the XLA reference math off-TPU or for non-tile-aligned
shapes, exactly as the reference falls back from cuDNN to the mshadow
kernel (src/operator/convolution.cc cudnn_off path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

BLOCK_Q = 256
# Round-5 block sweep on v5e (bq x bk over {256,512,1024}x{256,512},
# forward, causal, D=128): bk=512 wins the FORWARD at every selected
# shape — 1.33x @S2048, 1.66x @S4096, 1.18x @S8192/GQA, 1.26x in the
# 16k streaming regime, 1.08x at the S=1024 selection threshold — with
# identical numerics (bf16 maxdiff 0.016 vs the XLA reference,
# unchanged). The backward kernels are insensitive to both block sizes
# (measured flat), so their cost model is untouched. bq=512 adds
# nothing over bq=256 once bk=512.
BLOCK_K = 512
# Selection gate (the cudnn-autotune "must not lose" contract): measured
# on v5e (examples/transformer/bench_transformer.py micro). With the
# round-5 bk=512 tiles the kernel wins from S=512 up — 1.45-1.57x at
# S=512, 2.9-3.4x at S=2048, 4.8-8.5x at S=4096 — and still loses at
# S=256 (0.78-0.93x: too few tiles to amortize the per-block softmax
# bookkeeping vs XLA's fused einsum). Gate re-placed accordingly
# (was 1024 when the 256-wide tiles made S=512 a 0.91x loss).
MIN_SEQ = 512
# Longest sequence whose K/V (one side) stays whole in VMEM: 8192 * 128
# lanes * 2B = 2 MiB per buffer, measured to fit alongside everything
# else; 16384 exceeds the 16 MiB scoped-VMEM limit (the compile error
# that motivated the streaming regime).
_RESIDENT_MAX = 8192
# Streaming superblock target size (keys or queries per grid step).
SUPER_TARGET = 4096
_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _split_super(t, block, target=None):
    """(super, n_super): split a sequence of length t (a multiple of
    `block`, per the kernel contract) into equal superblocks, each a
    multiple of `block`, sized as close to `target` as divisibility
    allows. The superblock is the unit resident in VMEM per grid step;
    `block` stays the unit of one in-kernel loop iteration."""
    target = target or SUPER_TARGET
    nblocks = t // block
    # a target below the block size would start nsup above nblocks and
    # the divisibility walk could never terminate; one block per
    # superblock is the finest legal split
    nsup = min(max(1, -(-t // target)), nblocks)
    while nblocks % nsup:
        nsup += 1
    return t // nsup, nsup


# --- forward, resident regime ----------------------------------------------

def _fa_kernel_res(q_ref, k_ref, v_ref, o_ref, *maybe_lse_ref, causal,
                   scale, block_k, offset):
    """One (batch*kv-head, group, q-block) grid cell. Writes O, and the
    per-row logsumexp when a ref for it is supplied (training forward —
    the blocked backward needs it; inference skips the extra HBM write).

    Grouped-query layout: q is (B*Hkv, G, Tq, D) against k/v (B*Hkv, Tk,
    D) — the G query heads sharing one kv head iterate in the grid's
    middle dim while the k/v block index stays fixed, so K/V are fetched
    into VMEM once per KV head, not once per query head (the h/hkv
    HBM-bandwidth saving GQA exists for). G=1 is standard MHA.

    ``offset`` = tk - tq: causal masking aligns the LAST query with the
    last key (kv-cache decode), matching the XLA paths' (tk - tq) query
    offset (attention.py dot_product_attention / _grouped_attention)."""
    q = q_ref[0, 0].astype(jnp.float32) * scale       # (BQ, D)
    bq = q.shape[0]
    tk = k_ref.shape[1]
    qi = pl.program_id(2)
    num_k_blocks = pl.cdiv(tk, block_k)

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (BQ, BK)
        if causal:
            q_pos = qi * bq + offset + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])                # (BQ, BK)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    init = (jnp.zeros((bq, d), jnp.float32),
            jnp.full((bq,), _NEG_INF, jnp.float32),
            jnp.zeros((bq,), jnp.float32))
    if causal:
        # only blocks at or left of the (offset) diagonal contribute
        hi = jax.lax.min(num_k_blocks,
                         pl.cdiv((qi + 1) * bq + offset, block_k))
    else:
        hi = num_k_blocks
    acc, m, l = jax.lax.fori_loop(0, hi, body, init)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    if maybe_lse_ref:
        maybe_lse_ref[0][0, 0, 0] = m + jnp.log(l)


# --- forward, streaming regime ---------------------------------------------

def _fa_kernel_stream(q_ref, k_ref, v_ref, o_ref, *rest, causal, scale,
                      block_k, offset, with_lse, num_super):
    """One (batch*kv-head, group, q-block, k-superblock) grid cell. K/V
    stream through the grid's innermost dim one superblock at a time, the
    kernel loops over its block_k tiles with the online-softmax state in
    registers, and the state is carried ACROSS supersteps in VMEM scratch
    (acc, running max, running sum). O/lse flush on the last superstep."""
    lse_ref = rest[0] if with_lse else None
    acc_ref, m_ref, l_ref = rest[-3:]
    bq = q_ref.shape[2]
    sk = k_ref.shape[1]                                # superblock size
    qi = pl.program_id(2)
    ski = pl.program_id(3)
    inner = pl.cdiv(sk, block_k)

    @pl.when(ski == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale    # (BQ, D)

        def body(kb, carry):
            acc, m_prev, l_prev = carry
            k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(
                jnp.float32)
            v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(
                jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # (BQ, BK)
            if causal:
                q_pos = qi * bq + offset + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                k_pos = (ski * sk + kb * block_k
                         + jax.lax.broadcasted_iota(
                             jnp.int32, (bq, block_k), 1))
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            m_cur = jnp.max(s, axis=1)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new[:, None])            # (BQ, BK)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc, m_new, l_new

        if causal:
            # only tiles at or left of the (offset) diagonal contribute
            hi = jnp.clip(
                pl.cdiv((qi + 1) * bq + offset - ski * sk, block_k),
                0, inner)
        else:
            hi = inner
        # run the superblock with a REGISTER-local carry (seeding the
        # loop from scratch refs measured 2x slower — Mosaic pins the
        # carry to VMEM), then merge with the running state through the
        # logsumexp once per superstep — the ring-attention shard merge
        d = q_ref.shape[-1]
        init = (jnp.zeros((bq, d), jnp.float32),
                jnp.full((bq,), _NEG_INF, jnp.float32),
                jnp.zeros((bq,), jnp.float32))
        acc_l, m_l, l_l = jax.lax.fori_loop(0, hi, body, init)
        m_prev, l_prev = m_ref[0], l_ref[0]
        m_new = jnp.maximum(m_prev, m_l)
        a_prev = jnp.exp(m_prev - m_new)
        a_l = jnp.exp(m_l - m_new)
        m_ref[0] = m_new
        l_ref[0] = l_prev * a_prev + l_l * a_l
        acc_ref[...] = (acc_ref[...] * a_prev[:, None]
                        + acc_l * a_l[:, None])

    if causal:
        # supersteps strictly right of the diagonal contribute nothing:
        # skip the compute (their K/V fetch is also elided — the index
        # map clamps to the diagonal superblock, and Pallas only issues
        # a DMA when the block index CHANGES)
        pl.when(ski * sk <= qi * bq + offset + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ski == num_super - 1)
    def _finalize():
        l = l_ref[0]
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        if with_lse:
            lse_ref[0, 0, 0] = m_ref[0] + jnp.log(l)


def _kv_stream_idx(block_q, super_k, offset, causal):
    """Index map for K/V superblocks streamed under a (b, g, qi, ski)
    grid. Causal grids clamp ski to this q-block's diagonal superblock so
    the fully-masked tail re-addresses the same superblock (no DMA) while
    the kernel skips its compute."""
    if not causal:
        return lambda b, gi, qi, ski: (b, ski, 0)

    def idx(b, gi, qi, ski):
        hi = jax.lax.div(qi * block_q + block_q - 1 + offset, super_k)
        return (b, jnp.minimum(ski, hi), 0)

    return idx


def _fa_forward(q, k, v, causal, scale, interpret, with_lse=False):
    """q: (B*Hkv, G, Tq, D); k/v: (B*Hkv, Tk, D). Returns (B*Hkv, G, Tq,
    D) [+ lse (B*Hkv, G, 1, Tq) — the singleton keeps the last two block
    dims TPU-tileable]."""
    bkv, g, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(BLOCK_Q, tq)
    block_k = _pick_block(tk, BLOCK_K)
    resident = tk <= _RESIDENT_MAX
    kwargs = {}
    out_specs3 = [pl.BlockSpec((1, 1, block_q, d),
                               lambda b, gi, i: (b, gi, i, 0))]
    out_specs4 = [pl.BlockSpec((1, 1, block_q, d),
                               lambda b, gi, i, ski: (b, gi, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((bkv, g, tq, d), q.dtype)]
    if with_lse:
        # (bkv, g, 1, tq): TPU block rules need the last two block dims
        # divisible by (8, 128) or EQUAL to the array dims — the
        # singleton third dim gives (1, BQ) blocks with 1 == array dim
        out_specs3.append(pl.BlockSpec((1, 1, 1, block_q),
                                       lambda b, gi, i: (b, gi, 0, i)))
        out_specs4.append(pl.BlockSpec((1, 1, 1, block_q),
                                       lambda b, gi, i, ski: (b, gi, 0, i)))
        out_shape.append(jax.ShapeDtypeStruct((bkv, g, 1, tq),
                                              jnp.float32))
    cost = pl.CostEstimate(
        flops=4 * bkv * g * tq * tk * d,
        bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
        transcendentals=bkv * g * tq * tk,
    )
    if resident:
        kernel = functools.partial(_fa_kernel_res, causal=causal,
                                   scale=scale, block_k=block_k,
                                   offset=tk - tq)
        if pltpu is not None and not interpret:
            kwargs["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary"))
        res = pl.pallas_call(
            kernel,
            grid=(bkv, g, pl.cdiv(tq, block_q)),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, gi, i: (b, gi, i, 0)),
                # k/v block index ignores (gi, i): Pallas re-fetches only
                # on index change, so K/V stream from HBM once per KV head
                pl.BlockSpec((1, tk, d), lambda b, gi, i: (b, 0, 0)),
                pl.BlockSpec((1, tk, d), lambda b, gi, i: (b, 0, 0)),
            ],
            out_specs=out_specs3,
            out_shape=out_shape,
            cost_estimate=cost,
            interpret=interpret,
            **kwargs,
        )(q, k, v)
        return (res[0], res[1]) if with_lse else res[0]
    if pltpu is None:  # pragma: no cover - guarded by flash_attention()
        raise RuntimeError("pallas TPU backend unavailable")
    super_k, num_super = _split_super(tk, block_k)
    kernel = functools.partial(_fa_kernel_stream, causal=causal,
                               scale=scale, block_k=block_k,
                               offset=tk - tq, with_lse=with_lse,
                               num_super=num_super)
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary"))
    kv_idx = _kv_stream_idx(block_q, super_k, tk - tq, causal)
    res = pl.pallas_call(
        kernel,
        grid=(bkv, g, pl.cdiv(tq, block_q), num_super),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, gi, i, ski: (b, gi, i, 0)),
            pl.BlockSpec((1, super_k, d), kv_idx),
            pl.BlockSpec((1, super_k, d), kv_idx),
        ],
        out_specs=out_specs4,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
            pltpu.VMEM((1, block_q), jnp.float32),     # running max
            pltpu.VMEM((1, block_q), jnp.float32),     # running sum
        ],
        cost_estimate=cost,
        interpret=interpret,
        **kwargs,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else res[0]


# --- blocked backward (FlashAttention-2 style: no S^2 materialization) ------

def _fa_bwd_dq_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                          dq_ref, *, causal, scale, block_k, offset):
    """dQ for one (batch*kv-head, group, q-block): stream k/v blocks,
    rebuild p from the saved logsumexp, dq += (p * (dO v^T - D)) @ k *
    scale."""
    q = q_ref[0, 0].astype(jnp.float32)            # (BQ, D)
    do = do_ref[0, 0].astype(jnp.float32)          # (BQ, D)
    lse = lse_ref[0, 0, 0]                         # (BQ,)
    dvec = dvec_ref[0, 0, 0]                       # (BQ,)
    bq = q.shape[0]
    tk = k_ref.shape[1]
    qi = pl.program_id(2)
    num_k_blocks = pl.cdiv(tk, block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * bq + offset + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])              # (BQ, BK), rows sum<=1
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec[:, None])
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    hi = (jax.lax.min(num_k_blocks,
                      pl.cdiv((qi + 1) * bq + offset, block_k))
          if causal else num_k_blocks)
    dq = jax.lax.fori_loop(0, hi, body,
                           jnp.zeros((bq, q.shape[1]), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _fa_bwd_dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref,
                             dvec_ref, dq_ref, dq_acc_ref, *, causal,
                             scale, block_k, offset, num_super):
    """dQ for one (batch*kv-head, group, q-block): k/v SUPERBLOCKS stream
    through the grid's innermost dim, the kernel loops their block_k
    tiles rebuilding p from the saved logsumexp, and dq accumulates
    across supersteps in VMEM scratch, flushed on the last superstep."""
    bq = q_ref.shape[2]
    sk = k_ref.shape[1]
    qi = pl.program_id(2)
    ski = pl.program_id(3)
    inner = pl.cdiv(sk, block_k)

    @pl.when(ski == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)        # (BQ, D)
        do = do_ref[0, 0].astype(jnp.float32)      # (BQ, D)
        lse = lse_ref[0, 0, 0]                     # (BQ,)
        dvec = dvec_ref[0, 0, 0]                   # (BQ,)

        def body(kb, dq):
            k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(
                jnp.float32)
            v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(
                jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                q_pos = qi * bq + offset + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                k_pos = (ski * sk + kb * block_k
                         + jax.lax.broadcasted_iota(
                             jnp.int32, (bq, block_k), 1))
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            p = jnp.exp(s - lse[:, None])          # (BQ, BK), rows sum<=1
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - dvec[:, None])
            return dq + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            hi = jnp.clip(
                pl.cdiv((qi + 1) * bq + offset - ski * sk, block_k),
                0, inner)
        else:
            hi = inner
        # register-local accumulation, one scratch add per superstep
        # (seeding the loop carry from scratch pins it to VMEM — see the
        # forward kernel's note)
        dq_l = jax.lax.fori_loop(
            0, hi, body,
            jnp.zeros((q_ref.shape[2], q_ref.shape[3]), jnp.float32))
        dq_acc_ref[...] += dq_l

    if causal:
        pl.when(ski * sk <= qi * bq + offset + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ski == num_super - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                           dk_ref, dv_ref, *, causal, scale, block_q,
                           offset):
    """dK/dV for one (batch*kv-head, k-block) pair: stream q/dO blocks.
    The grid's LAST dim iterates the query-head group sequentially,
    accumulating each group head's contribution into the same dk/dv
    block (the GQA kv gradient is the sum over its group).

    Known tradeoff of this layout: the q/do/lse/dvec block index changes
    every grid step, so those are re-fetched num_k_blocks times per
    group head (vs once in a (bkv, g, kb)-ordered grid — which would
    break the dk/dv accumulation, since Pallas only accumulates across
    CONSECUTIVE revisits of an output block). The kernel is MXU-bound at
    every selected shape, so the extra q-side DMA rides otherwise-idle
    bandwidth: measured fwd+bwd stays within 1-3% of the old full-H
    layout while temp HBM drops g-fold (docs/perf.md GQA table)."""
    k = k_ref[0].astype(jnp.float32)               # (BK, D)
    v = v_ref[0].astype(jnp.float32)               # (BK, D)
    bk = k.shape[0]
    tq = q_ref.shape[2]
    ki = pl.program_id(1)
    gi = pl.program_id(2)
    num_q_blocks = pl.cdiv(tq, block_q)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, 0, pl.ds(qb * block_q, block_q)]
        dvec = dvec_ref[0, 0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])              # (BQ, BK)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec[:, None])
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    # causal: q blocks whose last (offset) query position precedes this
    # k block's start contribute nothing (every entry masked)
    lo = (jax.lax.max(ki * bk - offset, 0) // block_q) if causal else 0
    d = k.shape[1]
    dk, dv = jax.lax.fori_loop(
        lo, num_q_blocks, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk = (dk * scale).astype(dk_ref.dtype)
    dv = dv.astype(dv_ref.dtype)

    # first group head initializes the output block; later ones add
    @pl.when(gi == 0)
    def _init():
        dk_ref[0] = dk
        dv_ref[0] = dv

    @pl.when(gi > 0)
    def _accum():
        dk_ref[0] += dk
        dv_ref[0] += dv


def _fa_bwd_dkv_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              dvec_ref, dk_ref, dv_ref, dk_acc_ref,
                              dv_acc_ref, *, causal, scale, block_q,
                              offset, g, num_q_super):
    """dK/dV for one (batch*kv-head, k-block) pair: q/dO/lse/D stream
    through the two inner grid dims (group head, then q-SUPERBLOCK, whose
    block_q tiles the kernel loops over) while K/V stay resident, and
    dk/dv accumulate across ALL of them in f32 VMEM scratch — the GQA kv
    gradient is the sum over the group — flushed once on the final
    (group, q-superblock) step. Nothing in VMEM scales with total
    sequence length."""
    bk = k_ref.shape[1]
    sq = q_ref.shape[2]                            # q superblock size
    ki = pl.program_id(1)
    gi = pl.program_id(2)
    qsi = pl.program_id(3)
    inner = pl.cdiv(sq, block_q)

    @pl.when((gi == 0) & (qsi == 0))
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _compute():
        k = k_ref[0].astype(jnp.float32)           # (BK, D)
        v = v_ref[0].astype(jnp.float32)

        def body(qb, carry):
            dk, dv = carry
            q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(
                jnp.float32)
            do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(
                jnp.float32)
            lse = lse_ref[0, 0, 0, pl.ds(qb * block_q, block_q)]
            dvec = dvec_ref[0, 0, 0, pl.ds(qb * block_q, block_q)]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                q_pos = (qsi * sq + qb * block_q + offset
                         + jax.lax.broadcasted_iota(
                             jnp.int32, (block_q, bk), 0))
                k_pos = ki * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 1)
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            p = jnp.exp(s - lse[:, None])          # (BQ, BK)
            dv = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - dvec[:, None])
            dk = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk, dv

        if causal:
            # tiles whose last (offset) query position precedes this k
            # block's start contribute nothing (every entry masked)
            lo = jnp.clip(
                jax.lax.div(ki * bk - offset - qsi * sq, block_q),
                0, inner)
        else:
            lo = 0
        # register-local accumulation, one scratch add per superstep
        d = k_ref.shape[2]
        dk_l, dv_l = jax.lax.fori_loop(
            lo, inner, body,
            (jnp.zeros((bk, d), jnp.float32),
             jnp.zeros((bk, d), jnp.float32)))
        dk_acc_ref[...] += dk_l
        dv_acc_ref[...] += dv_l

    if causal:
        # q superblocks entirely above the diagonal are skipped; their
        # q-side fetches are elided by the clamped index map
        pl.when(qsi * sq + sq - 1 + offset >= ki * bk)(_compute)
    else:
        _compute()

    @pl.when((gi == g - 1) & (qsi == num_q_super - 1))
    def _finalize():
        dk_ref[0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _fa_backward(q, k, v, o, lse, do, causal, scale, interpret,
                 g_lse=None):
    """q/o/do: (B*Hkv, G, Tq, D); k/v: (B*Hkv, Tk, D); lse: (B*Hkv, G, 1,
    Tq). Returns (dq like q, dk/dv like k/v) — dk/dv already summed over
    the query-head group inside the kernel."""
    bkv, g, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(BLOCK_Q, tq)
    block_k = _pick_block(tk, BLOCK_K)
    # D_i = rowsum(dO * O): one cheap fused XLA pass. A cotangent on the
    # logsumexp output folds in here: d(lse)/ds = p, so ds gains
    # +g_lse*p, i.e. D := D - g_lse (ring attention's merge
    # differentiates through lse).
    dvec = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1)[:, :, None, :]         # (bkv, g, 1, tq)
    if g_lse is not None:
        dvec = dvec - g_lse.astype(jnp.float32)
    kwargs3 = {}
    kwargs4 = {}
    if pltpu is not None and not interpret:
        kwargs3["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))
        kwargs4["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary"))
    dq_cost = pl.CostEstimate(
        flops=6 * bkv * g * tq * tk * d,
        bytes_accessed=(q.size + k.size + v.size + do.size)
        * q.dtype.itemsize,
        transcendentals=bkv * g * tq * tk)
    if tk <= _RESIDENT_MAX:
        dq = pl.pallas_call(
            functools.partial(_fa_bwd_dq_kernel_res, causal=causal,
                              scale=scale, block_k=block_k,
                              offset=tk - tq),
            grid=(bkv, g, pl.cdiv(tq, block_q)),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, gi, i: (b, gi, i, 0)),
                pl.BlockSpec((1, tk, d), lambda b, gi, i: (b, 0, 0)),
                pl.BlockSpec((1, tk, d), lambda b, gi, i: (b, 0, 0)),
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, gi, i: (b, gi, i, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda b, gi, i: (b, gi, 0, i)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda b, gi, i: (b, gi, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda b, gi, i: (b, gi, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bkv, g, tq, d), q.dtype),
            cost_estimate=dq_cost,
            interpret=interpret,
            **kwargs3,
        )(q, k, v, do, lse, dvec)
    else:
        if pltpu is None:  # pragma: no cover
            raise RuntimeError("pallas TPU backend unavailable")
        super_k, num_k_super = _split_super(tk, block_k)
        kv_idx = _kv_stream_idx(block_q, super_k, tk - tq, causal)
        dq = pl.pallas_call(
            functools.partial(_fa_bwd_dq_kernel_stream, causal=causal,
                              scale=scale, block_k=block_k,
                              offset=tk - tq, num_super=num_k_super),
            grid=(bkv, g, pl.cdiv(tq, block_q), num_k_super),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, gi, i, ski: (b, gi, i, 0)),
                pl.BlockSpec((1, super_k, d), kv_idx),
                pl.BlockSpec((1, super_k, d), kv_idx),
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, gi, i, ski: (b, gi, i, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda b, gi, i, ski: (b, gi, 0, i)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda b, gi, i, ski: (b, gi, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda b, gi, i, ski: (b, gi, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bkv, g, tq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            cost_estimate=dq_cost,
            interpret=interpret,
            **kwargs4,
        )(q, k, v, do, lse, dvec)

    dkv_cost = pl.CostEstimate(
        # 4 matmuls per (q,k) tile pair: s, p^T@dO, dO@v^T, ds^T@q
        flops=8 * bkv * g * tq * tk * d,
        bytes_accessed=(q.size + k.size + v.size + do.size)
        * q.dtype.itemsize,
        transcendentals=bkv * g * tq * tk)
    if tq <= _RESIDENT_MAX:
        # dk/dv accumulate over the group inside the kernel; for g > 1
        # the running sum lives in the output block, so keep it f32 and
        # cast after (bf16 += per group head would round g times)
        kv_acc_dtype = k.dtype if g == 1 else jnp.float32
        dk, dv = pl.pallas_call(
            functools.partial(_fa_bwd_dkv_kernel_res, causal=causal,
                              scale=scale, block_q=block_q,
                              offset=tk - tq),
            grid=(bkv, pl.cdiv(tk, block_k), g),
            in_specs=[
                pl.BlockSpec((1, 1, tq, d), lambda b, i, gi: (b, gi, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, gi: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, gi: (b, i, 0)),
                pl.BlockSpec((1, 1, tq, d), lambda b, i, gi: (b, gi, 0, 0)),
                pl.BlockSpec((1, 1, 1, tq), lambda b, i, gi: (b, gi, 0, 0)),
                pl.BlockSpec((1, 1, 1, tq), lambda b, i, gi: (b, gi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, gi: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, gi: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bkv, tk, d), kv_acc_dtype),
                jax.ShapeDtypeStruct((bkv, tk, d), kv_acc_dtype),
            ],
            cost_estimate=dkv_cost,
            interpret=interpret,
            **kwargs3,
        )(q, k, v, do, lse, dvec)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable")
    super_q, num_q_super = _split_super(tq, block_q)
    # causal: q superblocks strictly above this k block's diagonal are
    # fully masked; clamp their index so the dead steps re-address the
    # previous superblock (no DMA) while the kernel skips their compute
    if causal:
        def q_idx(b, i, gi, qsi):
            lo = jax.lax.div(jax.lax.max(i * block_k - (tk - tq), 0),
                             super_q)
            return (b, gi, jnp.maximum(qsi, lo), 0)

        def qrow_idx(b, i, gi, qsi):
            lo = jax.lax.div(jax.lax.max(i * block_k - (tk - tq), 0),
                             super_q)
            return (b, gi, 0, jnp.maximum(qsi, lo))
    else:
        q_idx = lambda b, i, gi, qsi: (b, gi, qsi, 0)      # noqa: E731
        qrow_idx = lambda b, i, gi, qsi: (b, gi, 0, qsi)   # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel_stream, causal=causal,
                          scale=scale, block_q=block_q, offset=tk - tq,
                          g=g, num_q_super=num_q_super),
        grid=(bkv, pl.cdiv(tk, block_k), g, num_q_super),
        in_specs=[
            pl.BlockSpec((1, 1, super_q, d), q_idx),
            pl.BlockSpec((1, block_k, d), lambda b, i, gi, qsi: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, gi, qsi: (b, i, 0)),
            pl.BlockSpec((1, 1, super_q, d), q_idx),
            pl.BlockSpec((1, 1, 1, super_q), qrow_idx),
            pl.BlockSpec((1, 1, 1, super_q), qrow_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, gi, qsi: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, gi, qsi: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bkv, tk, d), v.dtype),
        ],
        # dk/dv accumulate over the group AND all q superblocks in f32
        # scratch (a bf16 += per contribution would round many times);
        # single cast at the final flush
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        cost_estimate=dkv_cost,
        interpret=interpret,
        **kwargs4,
    )(q, k, v, do, lse, dvec)
    return dq, dk, dv


def _aligned(t, block):
    return t % min(block, t) == 0


# Finest K tile the kernels accept: the CONTRACT is divisibility by this,
# NOT by BLOCK_K — _pick_block falls back from the preferred (faster)
# 512-wide tile to 256 for lengths like 768/1280/2816, so raising
# BLOCK_K never narrows which shapes qualify (ring-attention chunks
# that are odd multiples of 256 keep their flash path).
_MIN_TILE_K = 256


def _pick_block(t, pref):
    """Largest tile in {pref, pref/2, ..., _MIN_TILE_K} dividing t
    (t itself when t < _MIN_TILE_K)."""
    b = min(pref, t)
    while b > _MIN_TILE_K and t % b:
        b //= 2
    return b


def kernel_qualifies(tq, tk, d, compiled=True, causal=False):
    """The kernel's CORRECTNESS contract: sequence lengths divide into
    whole blocks (a ragged final block would read padding into the
    softmax) — K at the finest `_MIN_TILE_K` granularity (the actual
    tile is picked per shape by `_pick_block`); the compiled path
    additionally needs a lane-aligned head_dim; causal calls need
    tq <= tk (with tq > tk the first tk-tq query rows are FULLY masked —
    the XLA path's finfo.min masking degrades to uniform attention
    there, while the kernel's l=0 would produce NaN). Shared by
    flash_attention() and ring_attention's per-shard selection so the
    two paths cannot drift."""
    return (_aligned(tq, BLOCK_Q) and _aligned(tk, _MIN_TILE_K)
            and (not causal or tq <= tk)
            and (not compiled or d % 128 == 0))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q3, k3, v3, causal, scale, interpret):
    return _fa_forward(q3, k3, v3, causal, scale, interpret)


def _flash_fwd(q3, k3, v3, causal, scale, interpret):
    out, lse = _fa_forward(q3, k3, v3, causal, scale, interpret,
                           with_lse=True)
    return out, (q3, k3, v3, out, lse)


def _flash_bwd(causal, scale, interpret, res, g):
    # Blocked FlashAttention-2 backward: rebuilds p per tile from the
    # saved logsumexp — never materializes the (Tq, Tk) score matrix, so
    # long-sequence TRAINING scales like the forward (docs/perf.md
    # attention section; previously this was recompute-through-the-
    # reference-math and the S^2 backward dominated at seq >= 4096).
    q3, k3, v3, o3, lse = res
    return _fa_backward(q3, k3, v3, o3, lse, g, causal, scale, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_with_lse(q4, k3, v3, causal, scale, interpret):
    """(out, lse (bkv, g, 1, tq)) variant — ring attention's per-shard
    compute merges across shards using the logsumexp, so lse is a REAL
    output with its own cotangent here (folded into the D-vector in
    backward)."""
    return _fa_forward(q4, k3, v3, causal, scale, interpret, with_lse=True)


def _flash_with_lse_fwd(q3, k3, v3, causal, scale, interpret):
    out, lse = _fa_forward(q3, k3, v3, causal, scale, interpret,
                           with_lse=True)
    return (out, lse), (q3, k3, v3, out, lse)


def _flash_with_lse_bwd(causal, scale, interpret, res, g):
    q3, k3, v3, o3, lse = res
    g_out, g_lse = g
    return _fa_backward(q3, k3, v3, o3, lse, g_out, causal, scale,
                        interpret, g_lse=g_lse)


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """Attention over q (B, H, T, D). Pallas on TPU, XLA reference
    otherwise.

    k/v may carry FEWER heads (B, Hkv, Tk, D) with Hkv dividing H
    (grouped-query / multi-query attention): the kernel grids the query
    heads of a group over the same VMEM-resident K/V block, so K/V HBM
    traffic shrinks by h/hkv — no jnp.repeat materialization. Query head
    i attends kv head i // (H/Hkv) (consecutive q heads share a kv head,
    the same convention as attention.py's grouped einsum)."""
    from .. import attention as _att
    from . import on_tpu

    b, h, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError("q heads %d not divisible by kv heads %d"
                         % (h, hkv))
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def fallback():
        if hkv != h:
            return _att._grouped_attention(q, k, v, hkv, causal,
                                           scale=scale)
        return _att.dot_product_attention(q, k, v, causal=causal,
                                          scale=scale)

    # kernel_qualifies = the correctness contract; MIN_SEQ = the measured
    # perf threshold (auto mode only)
    if pltpu is None and (tq > _RESIDENT_MAX or tk > _RESIDENT_MAX):
        # the streaming kernels carry state in pltpu.VMEM scratch (both
        # compiled and interpret mode) — without the TPU pallas backend,
        # XLA path
        return fallback()
    if interpret is None:
        if not (on_tpu()
                and kernel_qualifies(tq, tk, d, causal=causal)
                and tq >= MIN_SEQ):
            return fallback()
        interpret = False
    elif not kernel_qualifies(tq, tk, d, compiled=not interpret,
                              causal=causal):
        # explicit interpret=True/False forces the kernel past the
        # MIN_SEQ perf gate (tests/benches), but never past the block
        # contract
        return fallback()

    g = h // hkv
    out = _flash(q.reshape(b * hkv, g, tq, d),
                 k.reshape(b * hkv, tk, d),
                 v.reshape(b * hkv, tk, d), causal, scale, interpret)
    return out.reshape(b, h, tq, d)
