"""Hand-written Pallas TPU kernels — the framework's fast-path layer.

Layering mirrors the reference's cuDNN strategy (SURVEY §2.1 #16,
src/operator/cudnn_*.h): every op has a portable XLA reference
implementation, and a Pallas kernel is selected when the backend is TPU and
the shapes qualify; otherwise the reference path runs. Selection is
centralized in :func:`use_pallas` (the analogue of the cudnn_algoreg
autotune gate, cudnn_algoreg-inl.h).
"""
import functools
import os

import jax

from . import flash_attention  # noqa: F401
from . import lstm  # noqa: F401
from . import fused_update  # noqa: F401


@functools.lru_cache(None)
def on_tpu() -> bool:
    if os.environ.get("MXNET_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
