"""Pallas LSTM step kernel — the cuDNN-RNN fast-path analogue.

The fused RNN op (ops/rnn_fused.py) hoists input projections out of its
time scan; what remains per step is ``h @ Wh^T`` plus four gate
nonlinearities and the cell update. This kernel fuses all of that in one
VMEM round-trip: the recurrent weight tile feeds the MXU while gate math
runs on the VPU, instead of XLA's matmul + separate elementwise kernels.

Mirrors the reference's layering where CuDNNRNNOp replaces the generic path
on qualifying hardware (src/operator/cudnn_rnn-inl.h:22).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _step_kernel(ib_ref, h_ref, c_ref, wh_ref, h_out_ref, c_out_ref, *, hidden):
    h_prev = h_ref[:]
    gates = ib_ref[:] + jax.lax.dot_general(
        h_prev, wh_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(gates[:, 0 * hidden:1 * hidden])
    f = jax.nn.sigmoid(gates[:, 1 * hidden:2 * hidden])
    g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(gates[:, 3 * hidden:4 * hidden])
    c = f * c_ref[:].astype(jnp.float32) + i * g
    c_out_ref[:] = c.astype(c_out_ref.dtype)
    h_out_ref[:] = (o * jnp.tanh(c)).astype(h_out_ref.dtype)


def lstm_step(ib, h_prev, c_prev, wh, interpret=False):
    """One fused LSTM step. ib: (N, 4H) pre-projected input+bias;
    h_prev/c_prev: (N, H); wh: (4H, H). Returns (h, c)."""
    n, h4 = ib.shape
    hidden = h4 // 4
    out = pl.pallas_call(
        functools.partial(_step_kernel, hidden=hidden),
        out_shape=(jax.ShapeDtypeStruct((n, hidden), h_prev.dtype),
                   jax.ShapeDtypeStruct((n, hidden), c_prev.dtype)),
        interpret=interpret,
    )(ib, h_prev, c_prev, wh)
    return out


def use_for(n, hidden):
    """Qualify shapes: lanes aligned, weights fit VMEM comfortably."""
    from . import on_tpu
    return (on_tpu() and hidden % 128 == 0 and n % 8 == 0
            and 4 * hidden * hidden * 4 <= 8 * 1024 * 1024)
