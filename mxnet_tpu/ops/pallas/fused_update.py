"""Fused optimizer update kernels.

The reference ships fused sgd/adam/rmsprop update ops
(src/operator/tensor/optimizer_op.cc) so the optimizer step is one kernel
per weight. On TPU, XLA already fuses the jnp formulations inside the jitted
step; these Pallas versions additionally guarantee single-pass HBM traffic
with in-place buffer aliasing (input_output_aliases ≡ kWriteInplace), used
by the imperative kvstore/optimizer path where each update runs standalone
outside a larger jit region.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _sgd_mom_kernel(w_ref, g_ref, m_ref, w_out, m_out, *, lr, momentum, wd,
                    rescale, clip):
    g = g_ref[:].astype(jnp.float32) * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    w = w_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32) * momentum - lr * (g + wd * w)
    m_out[:] = m.astype(m_out.dtype)
    w_out[:] = (w + m).astype(w_out.dtype)


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, interpret=False):
    """Fused momentum SGD (reference sgd_mom_update, optimizer_op.cc).
    Donates weight and momentum buffers — true in-place update."""
    kernel = functools.partial(_sgd_mom_kernel, lr=lr, momentum=momentum,
                               wd=wd, rescale=rescale_grad, clip=clip_gradient)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(weight.shape, weight.dtype),
                   jax.ShapeDtypeStruct(mom.shape, mom.dtype)),
        input_output_aliases={0: 0, 2: 1},
        interpret=interpret,
    )(weight, grad, mom)


def _adam_kernel(w_ref, g_ref, m_ref, v_ref, w_out, m_out, v_out, *, lr,
                 beta1, beta2, eps, wd, rescale, clip):
    g = g_ref[:].astype(jnp.float32) * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    w = w_ref[:].astype(jnp.float32)
    g = g + wd * w
    m = beta1 * m_ref[:].astype(jnp.float32) + (1 - beta1) * g
    v = beta2 * v_ref[:].astype(jnp.float32) + (1 - beta2) * g * g
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v.astype(v_out.dtype)
    w_out[:] = (w - lr * m / (jnp.sqrt(v) + eps)).astype(w_out.dtype)


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                interpret=False):
    """Fused Adam (reference adam_update, optimizer_op.cc); lr must carry
    the bias-correction factor, as in the reference Python optimizer."""
    kernel = functools.partial(_adam_kernel, lr=lr, beta1=beta1, beta2=beta2,
                               eps=epsilon, wd=wd, rescale=rescale_grad,
                               clip=clip_gradient)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(weight.shape, weight.dtype),
                   jax.ShapeDtypeStruct(mean.shape, mean.dtype),
                   jax.ShapeDtypeStruct(var.shape, var.dtype)),
        input_output_aliases={0: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(weight, grad, mean, var)
