"""Pallas weight-gradient kernel for small-window convolutions.

The backward-weight conv is contraction-shaped — output (kh, kw, C, K) is
tiny, the reduction runs over N*OH*OW — and XLA's emitter leaves
throughput on the floor for part of the 3x3 family (measured per shape in
tools/bench_conv_bwd.py; docs/perf.md ceiling analysis). This kernel
reformulates dW as ONE tall matmul per grid cell:

    for each image block: xcat[(l), (kh*kw*C)] = concat of the kh*kw
    shifted views of the (pre-padded) input; dW += xcat^T @ dY_flat

so the MXU sees an (ksz*ksz*C, L) x (L, K) contraction — M = 9C instead
of nine M = C passes, which is what makes C=64..128 layers profitable
(a lone (64, L) x (L, 64) matmul uses a quarter of the 128x128 array).

Layout: NHWC inside the kernel (the MXU-native layout XLA itself
relayouts to); the op-level fast path transposes at the boundary and
lets XLA fuse the transposes into neighbors. f32 accumulation across
grid steps (grid iterations are sequential on TPU), bf16 operands.

Selection follows the measured table (must-not-lose, the
cudnn-algoreg-inl.h contract): see use_wgrad_for().
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _wgrad_kernel(x_ref, dy_ref, o_ref, *, ksz, stride, bn):
    """One (k-block, image-block) grid cell.

    x_ref: (BN, HP, WP, C) pre-padded input block (HP = OH*s + ksz - s)
    dy_ref: (BN, OH, OW, BK)
    o_ref: (ksz*ksz*C, BK) f32 accumulator (same block for every cell of
           a given k-block; init on the first image block)
    """
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    x = x_ref[:]                       # (BN, HP, WP, C)
    dy = dy_ref[:]                     # (BN, OH, OW, BK)
    _, oh, ow, bk = dy.shape
    c = x.shape[-1]
    dyf = dy.reshape(bn * oh * ow, bk)

    def shift_view(kh, kw):
        if stride == 1:
            xs = x[:, kh:kh + oh, kw:kw + ow, :]
        else:
            # strided sampling via reshape-split (Mosaic-friendly: no
            # strided slice): rows kh, kh+s, ... kh+(oh-1)*s
            xs = x[:, kh:kh + oh * stride, kw:kw + ow * stride, :]
            xs = xs.reshape(bn, oh, stride, ow, stride, c)[:, :, 0, :, 0, :]
        return xs.reshape(bn * oh * ow, c)

    if c < 128:
        # small-C: a lone (C, L)x(L, K) pass wastes MXU rows; concatenate
        # the shifts so M = ksz*ksz*C fills the array
        xcat = jnp.concatenate(
            [shift_view(kh, kw) for kh in range(ksz) for kw in range(ksz)],
            axis=1)                              # (L, ksz*ksz*C)
        o_ref[:] += jax.lax.dot_general(
            xcat, dyf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (ksz*ksz*C, BK)
    else:
        # large-C: per-shift dots already fill the MXU, and skipping the
        # concatenation halves the kernel's VMEM footprint
        for kh in range(ksz):
            for kw in range(ksz):
                part = jax.lax.dot_general(
                    shift_view(kh, kw), dyf, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)      # (C, BK)
                idx = kh * ksz + kw
                o_ref[pl.ds(idx * c, c), :] += part


def conv_wgrad(x, dy, ksz, stride=1, pad=None, block_n=None, block_k=None,
               interpret=False):
    """dW for conv(x, W) with an (ksz, ksz) window, ``stride``, symmetric
    ``pad`` (default SAME-style (ksz-1)//2).

    x: (N, H, W, C) — NHWC; dy: (N, OH, OW, K). Returns (ksz, ksz, C, K)
    f32 (HWIO), the caller transposes to its layout.
    """
    n, h, w, c = x.shape
    _, oh, ow, k = dy.shape
    if pad is None:
        pad = (ksz - 1) // 2
    # pre-pad in XLA (one fused pad); kernel sees the full window field
    # (+ksz-1 so every shift can slice oh*stride rows for the reshape-
    # based strided sampling, clamp-free)
    hp = oh * stride + ksz - 1
    wp = ow * stride + ksz - 1
    xp = jnp.pad(x, ((0, 0), (pad, hp - h - pad), (pad, wp - w - pad),
                     (0, 0)))
    if block_n is None:
        # target ~1.5k-long contractions per cell; Mosaic's scoped-VMEM
        # stack holds the shift-view copies, so the budget is tighter
        # than the raw block sizes suggest (empirical: bn*oh*ow ≤ ~1600
        # compiles across the ResNet family)
        block_n = max(1, min(n, 1600 // max(1, oh * ow)))
        while n % block_n:
            block_n -= 1
    if block_k is None:
        block_k = k if (ksz * ksz * c * k * 4 <= 6 * 2 ** 20) else \
            max(128, k // 2)
        while k % block_k:
            block_k //= 2
    kernel = functools.partial(_wgrad_kernel, ksz=ksz, stride=stride,
                               bn=block_n)
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    out = pl.pallas_call(
        kernel,
        grid=(k // block_k, n // block_n),
        in_specs=[
            pl.BlockSpec((block_n, hp, wp, c), lambda kb, nb: (nb, 0, 0, 0)),
            pl.BlockSpec((block_n, oh, ow, block_k),
                         lambda kb, nb: (nb, 0, 0, kb)),
        ],
        out_specs=pl.BlockSpec((ksz * ksz * c, block_k),
                               lambda kb, nb: (0, kb)),
        out_shape=jax.ShapeDtypeStruct((ksz * ksz * c, k), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * oh * ow * c * k * ksz * ksz,
            bytes_accessed=(xp.size * (k // block_k) + dy.size) * 2,
            transcendentals=0,
        ),
        interpret=interpret,
        **kwargs,
    )(xp.astype(jnp.bfloat16), dy.astype(jnp.bfloat16))
    return out.reshape(ksz, ksz, c, k)


def use_wgrad_for(c, k, oh, ksz, stride):
    """Measured-selection predicate (tools/bench_conv_bwd.py table in
    docs/perf.md): the kernel is wired only where it beats XLA's
    weight-grad emitter on this chip family."""
    if ksz != 3:
        return False
    return (c, k, stride) in _WGRAD_WINS


# (C, K, stride) combos where conv_wgrad measured faster than XLA.
# Round-3 result on v5e: EMPTY — XLA's weight-grad emitter won at every
# ResNet 3x3 shape (0.52-0.63x, table in docs/perf.md): the kernel pays
# nine shifted VMEM copies per input block where the emitter windows
# implicitly. Kept per the must-not-lose contract for chip generations
# where the balance differs; re-run tools/bench_conv_bwd.py to repopulate.
_WGRAD_WINS: set = set()
