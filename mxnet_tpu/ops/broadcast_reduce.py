"""Broadcast and reduction operators.

TPU-native equivalents of src/operator/tensor/broadcast_reduce_op*.{cc,h}
and the hand-written reduce kernels in broadcast_reduce-inl.{h,cuh}
(SURVEY §2.1 #17). On TPU there is nothing to hand-schedule: XLA lowers
jnp reductions/broadcasts straight to efficient tiled loops, so these are
thin declarative definitions.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import defop, alias


def _norm_axis(axis, ndim):
    if axis is None or axis == ():
        return None
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    return tuple(int(a) % ndim for a in axis)


def _reduce(name, fn, py_name=None, default_axis=None):
    spec = {"axis": default_axis, "keepdims": False, "exclude": False}

    def impl(attrs, data, _f=fn):
        axis = _norm_axis(attrs["axis"], data.ndim)
        if attrs.get("exclude") and axis is not None:
            axis = tuple(i for i in range(data.ndim) if i not in axis)
        return _f(data, axis=axis, keepdims=bool(attrs["keepdims"]))

    defop(name, arg_names=("data",), param_spec=spec, py_name=py_name or name)(impl)


_reduce("sum", jnp.sum)
alias("sum", "sum_axis")
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max)
alias("max", "max_axis")
_reduce("min", jnp.min)
alias("min", "min_axis")


@defop("norm", arg_names=("data",), param_spec={"ord": 2, "axis": None, "keepdims": False})
def _norm(attrs, data):
    """L2 (or L1) norm reduction (reference broadcast_reduce_op_value.cc norm)."""
    axis = _norm_axis(attrs["axis"], data.ndim)
    if attrs["ord"] == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=bool(attrs["keepdims"]))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=bool(attrs["keepdims"])))


def _arg_reduce(name, fn):
    @defop(name, arg_names=("data",), param_spec={"axis": None, "keepdims": False})
    def impl(attrs, data, _f=fn):
        axis = attrs["axis"]
        if axis is None:
            out = _f(data.reshape(-1), axis=0)
            return out.astype(data.dtype)
        out = _f(data, axis=int(axis))
        if attrs["keepdims"]:
            out = jnp.expand_dims(out, int(axis))
        # reference returns float indices (same dtype as input)
        return out.astype(data.dtype)


_arg_reduce("argmax", jnp.argmax)
_arg_reduce("argmin", jnp.argmin)


@defop("argmax_channel", arg_names=("data",), param_spec={})
def _argmax_channel(attrs, data):
    """argmax over axis 1 (reference broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(data.dtype)


@defop("pick", arg_names=("data", "index"), no_grad_inputs=("index",),
       param_spec={"axis": -1, "keepdims": False, "mode": "clip"})
def _pick(attrs, data, index):
    """Pick one element per (n-1)-dim index position along ``axis``;
    out-of-range indices clip to the last element or wrap, per ``mode``
    (reference broadcast_reduce_op_index.cc pick)."""
    ax = attrs["axis"]
    ax = data.ndim - 1 if ax is None else int(ax) % data.ndim
    if attrs["mode"] == "wrap":
        idx = jnp.mod(index.astype(jnp.int32), data.shape[ax])
    else:
        idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[ax] - 1)
    # indices may come keepdims-shaped (size-1 at `axis`) or squeezed
    if idx.ndim == data.ndim - 1:
        idx = jnp.expand_dims(idx, ax)
    out = jnp.take_along_axis(data, idx, axis=ax)
    return out if attrs["keepdims"] else jnp.squeeze(out, ax)


# --- broadcasting binary ops (reference elemwise_binary_broadcast_op*.cc) ---
def _broadcast_binary(name, fn):
    defop(name, arg_names=("lhs", "rhs"), param_spec={})(
        lambda attrs, lhs, rhs, _f=fn: _f(lhs, rhs)
    )


_broadcast_binary("broadcast_add", jnp.add)
alias("broadcast_add", "broadcast_plus")
_broadcast_binary("broadcast_sub", jnp.subtract)
alias("broadcast_sub", "broadcast_minus")
_broadcast_binary("broadcast_mul", jnp.multiply)
_broadcast_binary("broadcast_div", jnp.divide)
_broadcast_binary("broadcast_mod", jnp.mod)
_broadcast_binary("broadcast_power", jnp.power)
_broadcast_binary("broadcast_maximum", jnp.maximum)
_broadcast_binary("broadcast_minimum", jnp.minimum)
_broadcast_binary("broadcast_hypot", jnp.hypot)
_broadcast_binary("broadcast_equal", lambda a, b: (a == b).astype(a.dtype))
_broadcast_binary("broadcast_not_equal", lambda a, b: (a != b).astype(a.dtype))
_broadcast_binary("broadcast_greater", lambda a, b: (a > b).astype(a.dtype))
_broadcast_binary("broadcast_greater_equal", lambda a, b: (a >= b).astype(a.dtype))
_broadcast_binary("broadcast_lesser", lambda a, b: (a < b).astype(a.dtype))
_broadcast_binary("broadcast_lesser_equal", lambda a, b: (a <= b).astype(a.dtype))
_broadcast_binary("broadcast_logical_and", lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype))
_broadcast_binary("broadcast_logical_or", lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype))
_broadcast_binary("broadcast_logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype(a.dtype))


@defop("broadcast_to", arg_names=("data",), param_spec={"shape": ()})
def _broadcast_to(attrs, data):
    """Broadcast to a target shape; 0 keeps the input dim (reference
    broadcast_reduce_op_value.cc broadcast_to)."""
    shape = tuple(attrs["shape"])
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@defop("broadcast_axis", arg_names=("data",), param_spec={"axis": (), "size": ()})
def _broadcast_axis(attrs, data):
    """Broadcast singleton axes to given sizes (reference broadcast_axis)."""
    axes = attrs["axis"]
    sizes = attrs["size"]
    if isinstance(axes, (int, np.integer)):
        axes, sizes = (axes,), (sizes,)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[int(a)] = int(s)
    return jnp.broadcast_to(data, tuple(tgt))


alias("broadcast_axis", "broadcast_axes")


@defop("where", arg_names=("condition", "x", "y"), param_spec={}, no_grad_inputs=("condition",))
def _where(attrs, condition, x, y):
    """Elementwise select (reference control_flow_op.cc where). Condition may
    be same-shape or a leading-axis vector selecting whole rows."""
    if condition.shape != x.shape and condition.ndim == 1:
        condition = condition.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(condition != 0, x, y)
