"""NHWC layout islands for the conv backbone (MXNET_CONV_LAYOUT).

The reference framework (and this repo's Symbol API) is NCHW/OIHW
end-to-end. On TPU that is the wrong resident layout: the vector lanes
are the LAST dimension (128 of them), so channels-last puts the channel
axis on the lanes and lets XLA lower convolutions onto the MXU without
relayouting around every conv. This module is the trace-time rewrite
that runs the whole conv backbone in NHWC/HWIO while keeping the
user-visible API, checkpoints, and per-channel quantization axes in the
reference NCHW/OIHW layout:

- **Islands, not per-op transposes.** A Convolution node seeds an
  island: its input is transposed to NHWC once (the stem boundary) and
  its output stays NHWC. Layout-agnostic neighbours — BatchNorm (the
  impl is axis-general), Activation, Pooling, Dropout, elementwise
  residual adds — PROPAGATE the tag instead of transposing, so the
  entire ResNet/VGG backbone is one island with exactly two boundary
  transposes (stem input, FC head), both of which XLA fuses into the
  adjacent ops.
- **Weights stay OIHW at rest.** The conv impl transposes OIHW -> HWIO
  *inside* the traced program (a single transpose per weight per
  program, hoisted/fused by XLA), so checkpoint save/load, the
  initializer shapes, `quant.py` per-channel axes (axis 0 = O) and
  `flops.py` MAC accounting are untouched.
- **Gated.** `MXNET_CONV_LAYOUT=nhwc` (default) | `nchw` (the bitwise
  reference arm — the pass is a no-op and every op sees exactly the
  pre-rewrite NCHW program). Read at `Symbol.build_eval` time like
  MXNET_BACKWARD_DO_MIRROR, so a rebind picks up a flip.

The pass runs inside the traced evaluator (`symbol.build_eval`), so the
transposes it inserts are ordinary jnp ops: autodiff produces the
matching transposed cotangents and gradients land in the reference
layout automatically. Values are tagged (a trace-time set of env keys),
never wrapped — an op that the pass does not know is a *boundary*: its
tagged inputs are transposed back to NCHW and its outputs are untagged,
which is always correct, merely slower.
"""
from __future__ import annotations

import os

import jax.numpy as jnp

#: ops that are elementwise/broadcast-safe when every non-scalar input
#: shares one shape: the tag propagates through them untouched. (A
#: mixed-shape broadcast — e.g. a (1, C, 1, 1) operand — would change
#: meaning under a transposed layout, so it falls to the boundary path.)
_ELEMWISE = frozenset((
    "Activation", "Dropout", "Cast", "clip", "relu", "sigmoid", "tanh",
    "abs", "negative", "exp", "log", "sqrt", "square",
    "_copy", "BlockGrad", "identity",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_maximum_scalar", "_minimum_scalar",
    "_power_scalar",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add", "broadcast_plus", "broadcast_sub", "broadcast_minus",
    "broadcast_mul", "broadcast_div", "broadcast_maximum",
    "broadcast_minimum",
))

_CONV_OPS = frozenset(("Convolution", "Convolution_v1"))
_BN_OPS = frozenset(("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm"))
_POOL_OPS = frozenset(("Pooling", "Pooling_v1"))

_EMPTY = frozenset()
_ALL0 = frozenset((0,))


def conv_layout() -> str:
    """The resident conv-backbone layout: ``nhwc`` (default) | ``nchw``."""
    v = os.environ.get("MXNET_CONV_LAYOUT", "nhwc").lower()
    return v if v in ("nhwc", "nchw") else "nhwc"


def enabled() -> bool:
    return conv_layout() == "nhwc"


def to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _is4d(v):
    return hasattr(v, "ndim") and v.ndim == 4


def adapt(op_name, attrs, vals, in_tags):
    """Trace-time layout adaptation for one graph node.

    ``vals`` are the node's input values (args then aux) as traced
    arrays; ``in_tags[i]`` is True when ``vals[i]`` is resident NHWC
    (logical NCHW). Returns ``(attrs', vals', tagged_out)`` where
    ``tagged_out`` is the frozenset of output indices that are resident
    NHWC. ``attrs'`` is either the original dict or a copy — the node's
    own attrs are never mutated.
    """
    vals = list(vals)

    if op_name in _CONV_OPS:
        kernel = attrs.get("kernel") or ()
        if (len(tuple(kernel)) == 2 and _is4d(vals[0])
                and attrs.get("layout") in (None, "NCHW")):
            if not in_tags[0]:
                vals[0] = to_nhwc(vals[0])  # island boundary: stem input
            return dict(attrs, layout="NHWC"), vals, _ALL0
        # 1-D/3-D or explicit-layout convs stay on the reference path
        return _boundary(attrs, vals, in_tags)

    if not any(in_tags):
        # untouched region: nothing to transpose, nothing to tag
        return attrs, vals, _EMPTY

    if op_name in _BN_OPS:
        data = vals[0]
        if in_tags[0] and _is4d(data) and int(attrs.get("axis", 1)) % 4 == 1:
            # the impl is axis-general; point it at channels-last. Only
            # out[0] is spatial — mean/var (output_mean_var) and the
            # moving-stat aux updates are per-channel 1-D either way.
            return dict(attrs, axis=3), vals, _ALL0
        return _boundary(attrs, vals, in_tags)

    if op_name in _POOL_OPS:
        if in_tags[0] and _is4d(vals[0]):
            return dict(attrs, layout="NHWC"), vals, _ALL0
        return _boundary(attrs, vals, in_tags)

    if op_name == "LeakyReLU" and attrs.get("act_type", "leaky") != "prelu":
        return attrs, vals, _ALL0 if in_tags[0] else _EMPTY

    if op_name in _ELEMWISE:
        # propagate when every non-scalar input shares the tagged shape
        # (the ResNet residual add); transpose equal-shape untagged
        # operands into the island instead of leaving it
        ref = next(v.shape for v, t in zip(vals, in_tags) if t)
        ok = True
        for i, v in enumerate(vals):
            if not hasattr(v, "ndim") or v.ndim == 0:
                continue
            if tuple(v.shape) != tuple(ref):
                ok = False
                break
        if ok:
            for i, (v, t) in enumerate(zip(vals, in_tags)):
                if not t and _is4d(v):
                    vals[i] = to_nhwc(v)
            return attrs, vals, _ALL0
        return _boundary(attrs, vals, in_tags)

    return _boundary(attrs, vals, in_tags)


def _boundary(attrs, vals, in_tags):
    """Leave the island: tagged inputs return to NCHW, outputs untagged."""
    for i, (v, t) in enumerate(zip(vals, in_tags)):
        if t:
            vals[i] = to_nchw(v)
    return attrs, vals, _EMPTY
