"""Contrib operators (reference src/operator/contrib/, SURVEY §2.1 #19):
CTCLoss, fft/ifft, count_sketch, quantize/dequantize, and the SSD /
Faster-RCNN detection ops (MultiBoxPrior/Target/Detection, Proposal).

TPU-first notes: the detection ops' control-flow-heavy matching/NMS is
expressed as fixed-iteration masked computation (lax.fori_loop + where)
instead of the reference's data-dependent CUDA loops, so everything stays
jittable with static shapes (SURVEY §7 risk register "Detection ops").
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import defop

_NEG = -1e9


# ---------------------------------------------------------------------------
# CTC loss (reference contrib/ctc_loss.cc, vendored warp-ctc)
# ---------------------------------------------------------------------------
@defop(
    "ctc_loss",
    arg_names=("data", "label"),
    param_spec={},
    no_grad_inputs=("label",),
    py_name="ctc_loss",
)
def _ctc_loss(attrs, data, label):
    """Connectionist temporal classification loss.

    data: (seq_len, batch, alphabet_size) activations (pre-softmax);
    label: (batch, label_len) int labels, 0 = blank-padding (reference uses
    0-padded labels with blank=0 at alphabet index 0? — the reference
    warp-ctc convention is blank=0 and labels in 1..alphabet-1).
    Returns per-example negative log likelihood, shape (batch,).
    Gradient flows via jax autodiff of the log-space forward recursion —
    equivalent to warp-ctc's alpha-beta gradient.
    """
    t_len, batch, nalpha = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)  # (T, B, A)
    lab = label.astype(jnp.int32)             # (B, L), 0-padded
    llen = jnp.sum((lab > 0).astype(jnp.int32), axis=1)  # (B,)
    lmax = lab.shape[1]
    s = 2 * lmax + 1
    # extended label sequence: blank, l1, blank, l2, ... blank
    ext = jnp.zeros((batch, s), jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    # positions beyond 2*llen are invalid
    pos = jnp.arange(s)[None, :]
    valid = pos < (2 * llen + 1)[:, None]

    # can-skip: ext[i] != blank and ext[i] != ext[i-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)))[:, :s]
    can_skip = (ext[:, :] != 0) & (ext != ext_m2) & (pos >= 2)

    alpha0 = jnp.full((batch, s), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(llen > 0, jnp.take_along_axis(
            logp[0], ext[:, 1:2], axis=1)[:, 0], _NEG))

    def step(alpha, lp_t):
        # lp_t: (B, A); gather per extended symbol: (B, S)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :s]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :s]
        stay = jnp.logaddexp(alpha, a_m1)
        full = jnp.where(can_skip, jnp.logaddexp(stay, a_m2), stay)
        new = full + emit
        new = jnp.where(valid, new, _NEG)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, logp[1:])
    # final: logaddexp of positions 2*llen and 2*llen-1
    last = jnp.take_along_axis(alpha, (2 * llen)[:, None], axis=1)[:, 0]
    last2_idx = jnp.maximum(2 * llen - 1, 0)
    last2 = jnp.take_along_axis(alpha, last2_idx[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(last, jnp.where(llen > 0, last2, _NEG))
    return -ll


# ---------------------------------------------------------------------------
# FFT (reference contrib/fft.cc — cuFFT wrapper, interleaved re/im output)
# ---------------------------------------------------------------------------
@defop("fft", arg_names=("data",), param_spec={"compute_size": 128})
def _fft(attrs, data):
    """FFT along the last axis; output interleaves real/imag → (..., 2d)
    (reference contrib/fft-inl.h output layout)."""
    f = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    return jnp.stack([f.real, f.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@defop("ifft", arg_names=("data",), param_spec={"compute_size": 128})
def _ifft(attrs, data):
    """Inverse FFT of interleaved re/im input (..., 2d) → (..., d).
    Matches the reference's unnormalized cuFFT inverse (scaled by d)."""
    d = data.shape[-1] // 2
    x = data.reshape(data.shape[:-1] + (d, 2))
    # complex math has no bf16: promote under low-precision compute
    xf = x.astype(jnp.float32)
    c = jax.lax.complex(xf[..., 0], xf[..., 1])
    return (jnp.fft.ifft(c, axis=-1).real * d).astype(data.dtype)


# ---------------------------------------------------------------------------
# count_sketch (reference contrib/count_sketch.cc)
# ---------------------------------------------------------------------------
@defop(
    "count_sketch",
    arg_names=("data", "h", "s"),
    param_spec={"out_dim": 0, "processing_batch_size": 32},
    no_grad_inputs=("h", "s"),
)
def _count_sketch(attrs, data, h, s):
    """Count-sketch projection: out[:, h[i]] += s[i] * data[:, i]
    (compact bilinear pooling building block)."""
    out_dim = int(attrs["out_dim"])
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    contrib = data * ss[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return out.at[:, hh].add(contrib)


# ---------------------------------------------------------------------------
# Quantization (reference contrib/quantize.cc)
# ---------------------------------------------------------------------------
#: symmetric target formats: dtype + the largest exactly-representable
#: magnitude the scale maps absmax onto (int8: 127; fp8-e4m3: 448)
SYMMETRIC_QMAX = {"int8": 127.0, "fp8_e4m3": 448.0}


def _symmetric_dtype(out_type: str):
    if out_type == "int8":
        return jnp.int8
    if out_type == "fp8_e4m3":
        return jnp.float8_e4m3fn
    raise ValueError("symmetric quantization supports int8/fp8_e4m3, got %r"
                     % (out_type,))


def quantize_symmetric(data, out_type: str = "int8", axis=None):
    """Symmetric quantization: ``q = round(data / scale)`` with
    ``scale = absmax / qmax``. ``axis=None`` is per-tensor (one scalar
    scale); an int (or tuple) names the CHANNEL axis/axes kept distinct —
    per-channel scales reduce over every *other* axis, the PTQ weight
    path (`mxnet_tpu.quant`). Returns ``(q, scale)`` with ``scale``
    keepdims-shaped so ``q * scale`` broadcasts back. Shared math for the
    ``quantize``/``dequantize`` contrib ops and the quant pass — one
    implementation, two surfaces."""
    qmax = SYMMETRIC_QMAX[out_type]
    if axis is None:
        reduce_axes = None
    else:
        keep = {a % data.ndim for a in
                (axis if isinstance(axis, (tuple, list)) else (axis,))}
        reduce_axes = tuple(a for a in range(data.ndim) if a not in keep)
    amax = jnp.max(jnp.abs(data), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12).astype(jnp.float32) / qmax
    q = jnp.clip(jnp.round(data / scale), -qmax, qmax) \
        if out_type == "int8" else data / scale
    return q.astype(_symmetric_dtype(out_type)), scale


def dequantize_symmetric(q, scale):
    """Inverse of :func:`quantize_symmetric` (f32 result)."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)


@defop(
    "quantize",
    arg_names=("data", "min_range", "max_range"),
    param_spec={"out_type": "uint8", "axis": None},
    num_outputs=3,
    no_grad_inputs=("min_range", "max_range"),
)
def _quantize(attrs, data, min_range, max_range):
    """Affine-quantize float→uint8 given calibration range; symmetric
    per-tensor/per-channel int8 / fp8-e4m3 with ``out_type`` set (the
    calibration ranges are then ignored — scales come from absmax over
    the non-``axis`` axes and are returned in the range outputs)."""
    out_type = attrs["out_type"]
    if out_type in SYMMETRIC_QMAX:
        q, scale = quantize_symmetric(data, out_type, attrs["axis"])
        return q, -scale * SYMMETRIC_QMAX[out_type], \
            scale * SYMMETRIC_QMAX[out_type]
    qmax = 255.0
    scale = qmax / (max_range - min_range)
    q = jnp.clip(jnp.round((data - min_range) * scale), 0, qmax)
    return q.astype(jnp.uint8), min_range, max_range


@defop(
    "dequantize",
    arg_names=("data", "min_range", "max_range"),
    param_spec={"out_type": "float32"},
    no_grad_inputs=("data", "min_range", "max_range"),
)
def _dequantize(attrs, data, min_range, max_range):
    if data.dtype in (jnp.int8, jnp.float8_e4m3fn):
        # symmetric path: max_range carries scale * qmax
        qmax = SYMMETRIC_QMAX["int8" if data.dtype == jnp.int8
                              else "fp8_e4m3"]
        return dequantize_symmetric(data, max_range / qmax)
    scale = (max_range - min_range) / 255.0
    return data.astype(jnp.float32) * scale + min_range


# ---------------------------------------------------------------------------
# SSD multibox ops (reference contrib/multibox_prior.cc, multibox_target.cc,
# multibox_detection.cc)
# ---------------------------------------------------------------------------
@defop(
    "MultiBoxPrior",
    arg_names=("data",),
    param_spec={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)},
    no_grad_inputs=("data",),
)
def _multibox_prior(attrs, data):
    """Anchor generation: (1, H*W*num_anchors, 4) corner-format boxes in
    [0,1], anchors = sizes + extra ratios (reference multibox_prior-inl.h:
    num_anchors = sizes + ratios - 1)."""
    h, w = data.shape[2], data.shape[3]
    sizes = [float(x) for x in attrs["sizes"]]
    ratios = [float(x) for x in attrs["ratios"]]
    steps = attrs["steps"]
    offs = attrs["offsets"]
    step_y = float(steps[0]) if float(steps[0]) > 0 else 1.0 / h
    step_x = float(steps[1]) if float(steps[1]) > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + float(offs[0])) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + float(offs[1])) * step_x
    # anchor (half-w, half-h) list: all sizes at ratio[0], then ratios[1:] at size[0]
    wh = []
    for sz in sizes:
        r = ratios[0]
        wh.append((sz * np.sqrt(r) / 2, sz / np.sqrt(r) / 2))
    for r in ratios[1:]:
        wh.append((sizes[0] * np.sqrt(r) / 2, sizes[0] / np.sqrt(r) / 2))
    wh = jnp.asarray(wh, jnp.float32)  # (A, 2): half_w, half_h
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
    centers = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]  # (H, W, 1, 2)
    half = wh[None, None, :, :]
    boxes = jnp.concatenate(
        [centers - half, centers + half], axis=-1)  # (H, W, A, 4) xmin..ymax
    boxes = boxes.reshape(1, -1, 4)
    if attrs["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _iou_matrix(a, b):
    """IoU between (N,4) and (M,4) corner boxes → (N,M)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    inter = jnp.prod(jnp.clip(br - tl, 0, None), axis=-1)
    area_a = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2], 0, None), axis=-1)
    area_b = jnp.prod(jnp.clip(b[:, 2:] - b[:, :2], 0, None), axis=-1)
    return inter / jnp.clip(area_a[:, None] + area_b[None, :] - inter, 1e-12)


@defop(
    "MultiBoxTarget",
    arg_names=("anchor", "label", "cls_pred"),
    param_spec={"overlap_threshold": 0.5, "ignore_label": -1.0,
                "negative_mining_ratio": -1.0, "negative_mining_thresh": 0.5,
                "minimum_negative_samples": 0, "variances": (0.1, 0.1, 0.2, 0.2)},
    num_outputs=3,
    no_grad_inputs=("anchor", "label", "cls_pred"),
)
def _multibox_target(attrs, anchor, label, cls_pred):
    """Anchor→ground-truth matching producing box regression targets, a
    regression mask, and per-anchor class targets (reference
    multibox_target-inl.h). label: (B, num_gt, 5) [cls, xmin, ymin, xmax,
    ymax], cls = -1 for padding."""
    anchors = anchor.reshape(-1, 4)
    na = anchors.shape[0]
    var = jnp.asarray([float(v) for v in attrs["variances"]], jnp.float32)
    thresh = float(attrs["overlap_threshold"])

    def per_image(lab):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_matrix(anchors, gt_boxes)              # (NA, NG)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                 # (NA,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= thresh
        # force-match: each valid gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)             # (NG,)
        forced = jnp.zeros(na, bool).at[best_anchor].set(gt_valid)
        forced_gt = jnp.zeros(na, jnp.int32).at[best_anchor].set(
            jnp.arange(lab.shape[0]))
        use_forced = forced
        gt_idx = jnp.where(use_forced, forced_gt, best_gt)
        pos = matched | use_forced
        g = gt_boxes[gt_idx]                              # (NA, 4)
        # encode center-offset targets
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.clip(g[:, 2] - g[:, 0], 1e-12)
        gh = jnp.clip(g[:, 3] - g[:, 1], 1e-12)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        t = jnp.stack([(gcx - acx) / aw / var[0], (gcy - acy) / ah / var[1],
                       jnp.log(gw / aw) / var[2], jnp.log(gh / ah) / var[3]],
                      axis=1)
        loc_target = jnp.where(pos[:, None], t, 0.0).reshape(-1)
        loc_mask = jnp.where(pos[:, None], 1.0, 0.0).repeat(4, axis=1)[:, :4].reshape(-1)
        cls_target = jnp.where(pos, lab[gt_idx, 0] + 1.0, 0.0)
        return loc_target, loc_mask, cls_target

    loc_t, loc_m, cls_t = jax.vmap(per_image)(label)
    return loc_t, loc_m, cls_t


def _nms_loop(boxes, scores, valid, iou_thresh, topk):
    """Greedy NMS with static iteration count: at each step pick the
    highest-score surviving box, emit it, suppress overlaps."""
    n = boxes.shape[0]
    topk = n if topk <= 0 else min(topk, n)

    def body(_, state):
        scores_live, keep = state
        i = jnp.argmax(scores_live)
        best = scores_live[i]
        iou = _iou_matrix(boxes[i][None], boxes)[0]
        suppress = (iou > iou_thresh) & (scores_live > _NEG)
        scores_live = jnp.where(suppress, _NEG, scores_live)
        scores_live = scores_live.at[i].set(_NEG)
        # OR-update: exhausted iterations re-select an index and must not
        # clear a previously kept box
        keep = keep.at[i].set(keep[i] | (best > _NEG))
        return scores_live, keep

    scores0 = jnp.where(valid, scores, _NEG)
    keep0 = jnp.zeros(n, bool)
    _, keep = jax.lax.fori_loop(0, topk, body, (scores0, keep0))
    return keep


@defop(
    "MultiBoxDetection",
    arg_names=("cls_prob", "loc_pred", "anchor"),
    param_spec={"clip": True, "threshold": 0.01, "background_id": 0,
                "nms_threshold": 0.5, "force_suppress": False,
                "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1},
    no_grad_inputs=("cls_prob", "loc_pred", "anchor"),
)
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + per-class NMS → (B, NA, 6) rows [cls_id, score, xmin, ymin,
    xmax, ymax]; cls_id = -1 marks suppressed rows (reference
    multibox_detection-inl.h)."""
    anchors = anchor.reshape(-1, 4)
    var = jnp.asarray([float(v) for v in attrs["variances"]], jnp.float32)
    bg = int(attrs["background_id"])
    thr = float(attrs["threshold"])
    nms_t = float(attrs["nms_threshold"])
    topk = int(attrs["nms_topk"])

    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def per_image(cp, lp):
        # cp: (num_classes, NA); lp: (NA*4,)
        l = lp.reshape(-1, 4)
        cx = l[:, 0] * var[0] * aw + acx
        cy = l[:, 1] * var[1] * ah + acy
        w = jnp.exp(l[:, 2] * var[2]) * aw / 2
        h = jnp.exp(l[:, 3] * var[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=1)
        if attrs["clip"]:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = jnp.where(
            jnp.arange(cp.shape[0])[:, None] == bg, -1.0, cp)  # mask bg row
        cls_id = jnp.argmax(scores, axis=0)
        score = jnp.max(scores, axis=0)
        valid = score > thr
        keep = _nms_loop(boxes, score, valid, nms_t, topk)
        # class id re-based past the background row (reference convention)
        out_cls = jnp.where(keep, (cls_id - (bg == 0)).astype(jnp.float32), -1.0)
        return jnp.concatenate(
            [out_cls[:, None], score[:, None], boxes], axis=1)

    return jax.vmap(per_image)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# Faster-RCNN proposal (reference contrib/proposal.cc)
# ---------------------------------------------------------------------------
@defop(
    "Proposal",
    arg_names=("cls_prob", "bbox_pred", "im_info"),
    param_spec={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                "threshold": 0.7, "rpn_min_size": 16,
                "scales": (4.0, 8.0, 16.0, 32.0), "ratios": (0.5, 1.0, 2.0),
                "feature_stride": 16, "output_score": False,
                "iou_loss": False},
    num_outputs=lambda attrs: 2 if attrs["output_score"] else 1,
    no_grad_inputs=("cls_prob", "bbox_pred", "im_info"),
)
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposal generation: anchors → bbox decode → clip → NMS → top-N
    rois (batch_idx, x1, y1, x2, y2). Static-shape NMS, batch size 1 as in
    the reference."""
    stride = int(attrs["feature_stride"])
    scales = [float(s) for s in attrs["scales"]]
    ratios = [float(r) for r in attrs["ratios"]]
    post_n = int(attrs["rpn_post_nms_top_n"])
    b, a2, h, w = cls_prob.shape
    na = a2 // 2

    # base anchors centered at (stride/2, stride/2)
    base = []
    ctr = (stride - 1) / 2.0
    size = stride * stride
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            base.append([ctr - (ws * s - 1) / 2, ctr - (hs * s - 1) / 2,
                         ctr + (ws * s - 1) / 2, ctr + (hs * s - 1) / 2])
    base = jnp.asarray(base, jnp.float32)  # (na, 4)

    shift_x = jnp.arange(w, dtype=jnp.float32) * stride
    shift_y = jnp.arange(h, dtype=jnp.float32) * stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    anchors = (base[None] + shifts).reshape(-1, 4)      # (h*w*na, 4)

    scores = cls_prob[0, na:].transpose(1, 2, 0).reshape(-1)  # fg scores
    deltas = bbox_pred[0].reshape(na, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    cx = deltas[:, 0] * aw + acx
    cy = deltas[:, 1] * ah + acy
    pw = jnp.exp(deltas[:, 2]) * aw
    ph = jnp.exp(deltas[:, 3]) * ah
    boxes = jnp.stack([cx - pw / 2, cy - ph / 2,
                       cx + pw / 2, cy + ph / 2], axis=1)
    im_h, im_w = im_info[0, 0], im_info[0, 1]
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                       jnp.clip(boxes[:, 1], 0, im_h - 1),
                       jnp.clip(boxes[:, 2], 0, im_w - 1),
                       jnp.clip(boxes[:, 3], 0, im_h - 1)], axis=1)
    min_size = float(attrs["rpn_min_size"]) * im_info[0, 2]
    ok = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
          & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
    keep = _nms_loop(boxes, scores, ok, float(attrs["threshold"]), post_n)
    score_rank = jnp.where(keep, scores, _NEG)
    _, top_idx = jax.lax.top_k(score_rank, post_n)
    rois = jnp.concatenate(
        [jnp.zeros((post_n, 1), jnp.float32), boxes[top_idx]], axis=1)
    if attrs["output_score"]:
        return rois, scores[top_idx][:, None]
    return rois


# ---------------------------------------------------------------------------
# Correlation (reference src/operator/correlation.cc / correlation-inl.h —
# the FlowNet cost-volume layer). TPU-native: the displacement window is a
# static (D*D)-way batch of channel-mean products, each an XLA-fused
# elementwise-multiply + reduce over a shifted view — no scalar loops, so
# the whole cost volume compiles to one fused HLO.
# ---------------------------------------------------------------------------
@defop(
    "Correlation",
    arg_names=("data1", "data2"),
    param_spec={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                "stride2": 1, "pad_size": 0, "is_multiply": True},
)
def _correlation(attrs, data1, data2):
    """Cost volume between two (B, C, H, W) feature maps.

    out[b, d, y, x] = mean over the kernel window and channels of
    data1[...y*s1, x*s1] (*|-) data2 shifted by displacement d, where d
    ranges over a (2*max_displacement/stride2+1)^2 grid. is_multiply=False
    uses absolute difference (reference CorrelationParam::is_multiply).
    """
    k = int(attrs["kernel_size"])
    md = int(attrs["max_displacement"])
    s1 = int(attrs["stride1"])
    s2 = int(attrs["stride2"])
    pad = int(attrs["pad_size"])
    b, c, h, w = data1.shape
    rad = k // 2
    d_per_side = md // s2
    disp = [i * s2 for i in range(-d_per_side, d_per_side + 1)]
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    # valid center positions: [border, size - border) stepped by stride1;
    # the border must fit displacement AND kernel radius TOGETHER — the
    # displaced patch extends to center + md + rad (reference
    # correlation-inl.h kernel_radius_ + max_displacement_ border; using
    # max(md, rad) both mis-sized the output for kernel_size > 1 and let
    # edge windows read clamped out-of-range values)
    border = md + rad
    ys = list(range(border, ph - border, s1))
    xs = list(range(border, pw - border, s1))
    out_h, out_w = len(ys), len(xs)
    if out_h == 0 or out_w == 0:
        raise MXNetError("Correlation: displacement/pad config leaves no "
                         "valid output positions")
    y0, x0 = ys[0], xs[0]

    def window(x, dy, dx):
        # (B, C, out_h*k, out_w*k) gather of the kernel windows at centers
        sl = jax.lax.dynamic_slice(
            x, (0, 0, y0 + dy - rad, x0 + dx - rad),
            (b, c, (out_h - 1) * s1 + k, (out_w - 1) * s1 + k))
        # extract k×k patches stepped by stride1
        patches = [sl[:, :, i:i + (out_h - 1) * s1 + 1:s1,
                      j:j + (out_w - 1) * s1 + 1:s1]
                   for i in range(k) for j in range(k)]
        return jnp.stack(patches, axis=2)  # (B, C, k*k, out_h, out_w)

    f1 = window(p1, 0, 0)
    maps = []
    for dy in disp:
        for dx in disp:
            f2 = window(p2, dy, dx)
            if attrs["is_multiply"]:
                m = jnp.mean(f1 * f2, axis=(1, 2))
            else:
                m = jnp.mean(jnp.abs(f1 - f2), axis=(1, 2))
            maps.append(m)
    return jnp.stack(maps, axis=1)  # (B, D*D, out_h, out_w)
