"""Operator library: importing this package registers every operator.

The registry (registry.py) is the single source of truth from which the
imperative (ndarray) and symbolic (symbol) user APIs are generated — the
TPU-native analogue of the reference's runtime op registry + generated
Python functions (python/mxnet/ndarray.py:28-39).
"""
from . import registry  # noqa: F401
from .registry import OP_REGISTRY, OpContext, OpDef, defop, get_op, alias  # noqa: F401

# Import order only matters for aliases; each module self-registers.
from . import elemwise  # noqa: F401
from . import broadcast_reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import init_random  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence  # noqa: F401
from . import shape_rules  # noqa: F401
from . import rnn_fused  # noqa: F401
from . import attention  # noqa: F401
from . import contrib  # noqa: F401
from . import custom  # noqa: F401
