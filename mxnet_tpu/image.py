"""mx.image — Python-side image pipeline.

Reimplementation of python/mxnet/image.py (SURVEY §2.4): composable
augmenters + ImageIter reading .rec files or image lists, decoding with
cv2 on the host. This is the flexible Python alternative to the native
C++ pipeline (io_iters.ImageRecordIter / native/recordio.cc), exactly as
the reference offers both (image.py:669 vs iter_image_recordio_2.cc).
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional

import numpy as np

from . import ndarray as nd
from . import recordio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an image byte buffer to an NDArray (H, W, C) uint8
    (reference image.py imdecode → src/io/image_io.cc)."""
    import cv2

    img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img)


def imresize(src, w, h, interp=1):
    import cv2

    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    return nd.array(cv2.resize(arr, (w, h), interpolation=interp))


def scale_down(src_size, size):
    """Scale size down to fit in src_size keeping aspect (reference
    image.py scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (reference image.py resize_short)."""
    import cv2

    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return nd.array(cv2.resize(arr, (new_w, new_h), interpolation=interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        import cv2

        out = cv2.resize(out, size, interpolation=interp)
    return nd.array(out)


def random_crop(src, size, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    arr = (src.asnumpy() if hasattr(src, "asnumpy")
           else np.asarray(src)).astype(np.float32)
    arr = arr - np.asarray(mean)
    if std is not None:
        arr = arr / np.asarray(std)
    return nd.array(arr)


# --- composable augmenters (reference image.py CreateAugmenter) -----------
class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(np.ascontiguousarray(src.asnumpy()[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __call__(self, src):
        return nd.array(src.asnumpy().astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(src.asnumpy().astype(np.float32) * alpha)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, inter_method=2):
    """Build the standard augmenter list (reference image.py
    CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Flexible Python image iterator over .rec or image-list files
    (reference image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, **kwargs)
        self.shuffle = shuffle
        self._rec = None
        self.imglist = []
        if path_imgrec:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = np.array(parts[1:1 + label_width], np.float32)
                    self.imglist.append((label, os.path.join(path_root,
                                                             parts[-1])))
        elif imglist:
            for label, fname in imglist:
                self.imglist.append((np.array(label, np.float32).reshape(-1),
                                     os.path.join(path_root, fname)))
        else:
            raise MXNetError("need path_imgrec, path_imglist, or imglist")
        self._order = list(range(len(self.imglist))) if self.imglist else None
        self._cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0
        if self._rec is not None:
            self._rec.reset()
        elif self.shuffle:
            pyrandom.shuffle(self._order)

    def next_sample(self):
        if self._rec is not None:
            buf = self._rec.read()
            if buf is None:
                raise StopIteration
            header, img = recordio.unpack(buf)
            lab = header.label
            return np.asarray(lab, np.float32).reshape(-1), img
        if self._cursor >= len(self.imglist):
            raise StopIteration
        label, fname = self.imglist[self._order[self._cursor]]
        self._cursor += 1
        with open(fname, "rb") as f:
            return label, f.read()

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.zeros((self.batch_size,), np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self.next_sample()
                img = imdecode(buf)
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = label.flat[0]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return DataBatch([nd.array(batch_data)], [nd.array(batch_label)],
                         pad=self.batch_size - i)
