"""mx.image — Python-side image pipeline.

Reimplementation of python/mxnet/image.py (SURVEY §2.4): composable
augmenters + ImageIter reading .rec files or image lists, decoding with
cv2 on the host. This is the flexible Python alternative to the native
C++ pipeline (io_iters.ImageRecordIter / native/recordio.cc), exactly as
the reference offers both (image.py:669 vs iter_image_recordio_2.cc).
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional

import numpy as np

from . import ndarray as nd
from . import recordio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an image byte buffer to an NDArray (H, W, C) uint8
    (reference image.py imdecode → src/io/image_io.cc)."""
    import cv2

    img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img)


def imresize(src, w, h, interp=1):
    import cv2

    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    return nd.array(cv2.resize(arr, (w, h), interpolation=interp))


def scale_down(src_size, size):
    """Scale size down to fit in src_size keeping aspect (reference
    image.py scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (reference image.py resize_short)."""
    import cv2

    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return nd.array(cv2.resize(arr, (new_w, new_h), interpolation=interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        import cv2

        out = cv2.resize(out, size, interpolation=interp)
    return nd.array(out)


def random_crop(src, size, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random crop with random area and aspect ratio (reference image.py
    random_size_crop; falls back to random_crop when the ratio draw leaves
    no admissible area)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_ratio = pyrandom.uniform(*ratio)
    if new_ratio * h > w:
        max_area = w * int(w / new_ratio)
    else:
        max_area = h * int(h * new_ratio)
    min_area = min_area * h * w
    if max_area < min_area:
        return random_crop(src, size, interp)
    new_area = pyrandom.uniform(min_area, max_area)
    new_w = int(np.sqrt(new_area * new_ratio))
    new_h = int(np.sqrt(new_area / new_ratio))
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def _rotate_arr(arr, angle, fill_value=255, interp=1):
    """numpy-in/numpy-out body of rotate_image (shared with the host data
    loaders, which must stay off the device)."""
    import cv2

    h, w = arr.shape[:2]
    a = np.cos(angle / 180.0 * np.pi)
    b = np.sin(angle / 180.0 * np.pi)
    M = np.zeros((2, 3), np.float32)
    M[0, 0], M[0, 1] = a, b
    M[1, 0], M[1, 1] = -b, a
    M[0, 2] = (w - (M[0, 0] * w + M[0, 1] * h)) / 2
    M[1, 2] = (h - (M[1, 0] * w + M[1, 1] * h)) / 2
    return cv2.warpAffine(arr, M, (w, h), flags=interp,
                          borderMode=cv2.BORDER_CONSTANT,
                          borderValue=(fill_value,) * 3)


def rotate_image(src, angle, fill_value=255, interp=1):
    """Rotate by ``angle`` degrees about the center, same output size,
    constant fill — the reference affine at scale=1/shear=0/aspect=1
    (src/io/image_aug_default.cc:215-246: M=[[cos,sin],[-sin,cos]] with the
    translation that centers the rotated image)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    return nd.array(_rotate_arr(arr, angle, fill_value, interp))


def _hsl_arr(arr, dh, ds, dl):
    """numpy-in/numpy-out body of hsl_shift (shared with the host data
    loaders)."""
    import cv2

    hls = cv2.cvtColor(arr.astype(np.uint8), cv2.COLOR_RGB2HLS).astype(np.int32)
    shifted = hls + np.array([dh, dl, ds], np.int32)
    limit = np.array([180, 255, 255], np.int32)
    shifted = np.clip(shifted, 0, limit).astype(np.uint8)
    return cv2.cvtColor(shifted, cv2.COLOR_HLS2RGB)


def hsl_shift(src, dh, ds, dl):
    """Add integer offsets to the H/S/L channels in 8-bit HLS space and
    clip — the reference color-space augmentation
    (src/io/image_aug_default.cc:297-316: per-pixel add of (h, l, s) with
    limits (180, 255, 255)). Input and output are uint8 RGB."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    return nd.array(_hsl_arr(arr, dh, ds, dl))


def center_crop(src, size, interp=2):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    arr = (src.asnumpy() if hasattr(src, "asnumpy")
           else np.asarray(src)).astype(np.float32)
    arr = arr - np.asarray(mean)
    if std is not None:
        arr = arr / np.asarray(std)
    return nd.array(arr)


# --- composable augmenters (reference image.py CreateAugmenter) -----------
class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(np.ascontiguousarray(src.asnumpy()[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __call__(self, src):
        return nd.array(src.asnumpy().astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(src.asnumpy().astype(np.float32) * alpha)


# Rec.601 luma weights shared by contrast/saturation jitter (reference
# image.py ColorJitterAug coef).
_GRAY_COEF = np.array([0.299, 0.587, 0.114], np.float32)


class ContrastJitterAug(Augmenter):
    """src*alpha + mean_gray*(1-alpha) (reference image.py ColorJitterAug
    contrast branch: gray = (3*(1-alpha)/size)*sum(src*coef))."""

    def __init__(self, contrast):
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = arr * _GRAY_COEF
        gray = (3.0 * (1.0 - alpha) / gray.size) * gray.sum()
        return nd.array(arr * alpha + gray)


class SaturationJitterAug(Augmenter):
    """Blend toward the per-pixel gray value (reference image.py
    ColorJitterAug saturation branch)."""

    def __init__(self, saturation):
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * _GRAY_COEF).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class RandomOrderAug(Augmenter):
    """Apply child augmenters in a freshly shuffled order each call
    (reference image.py RandomOrderAug)."""

    def __init__(self, ts):
        self.ts = list(ts)

    def __call__(self, src):
        order = list(self.ts)
        pyrandom.shuffle(order)
        for t in order:
            src = t(src)
        return src


def ColorJitterAug(brightness, contrast, saturation):
    """Random brightness/contrast/saturation jitter in random order
    (reference image.py ColorJitterAug)."""
    ts: List[Augmenter] = []
    if brightness > 0:
        ts.append(BrightnessJitterAug(brightness))
    if contrast > 0:
        ts.append(ContrastJitterAug(contrast))
    if saturation > 0:
        ts.append(SaturationJitterAug(saturation))
    return RandomOrderAug(ts)


class LightingAug(Augmenter):
    """PCA-based lighting noise (reference image.py LightingAug: alpha ~
    N(0, alphastd); src += eigvec @ (alpha * eigval))."""

    def __init__(self, alphastd, eigval, eigvec):
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return nd.array(src.asnumpy().astype(np.float32) + rgb)


# ImageNet PCA basis (reference image.py CreateAugmenter pca_noise block).
PCA_EIGVAL = np.array([55.46, 4.794, 1.148])
PCA_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]])


class HSLJitterAug(Augmenter):
    """Random additive jitter in 8-bit HLS space (native-path analogue:
    src/io/image_aug_default.cc random_h/s/l). Runs on uint8 RGB, so place
    it BEFORE CastAug in an augmenter chain."""

    def __init__(self, random_h=0, random_s=0, random_l=0):
        self.random_h = int(random_h)
        self.random_s = int(random_s)
        self.random_l = int(random_l)

    def __call__(self, src):
        dh = int(pyrandom.uniform(0, 1) * self.random_h * 2 - self.random_h)
        ds = int(pyrandom.uniform(0, 1) * self.random_s * 2 - self.random_s)
        dl = int(pyrandom.uniform(0, 1) * self.random_l * 2 - self.random_l)
        return hsl_shift(src, dh, ds, dl)


class RandomRotateAug(Augmenter):
    """Rotate by a random integer degree in [-max_rotate_angle,
    max_rotate_angle], or by the fixed ``rotate`` angle when set
    (reference image_aug_default.cc: ``rotate`` overrides
    ``max_rotate_angle``; constant ``fill_value`` border)."""

    def __init__(self, max_rotate_angle=0, rotate=-1, fill_value=255,
                 interp=1):
        self.max_rotate_angle = int(max_rotate_angle)
        self.rotate = rotate
        self.fill_value = fill_value
        self.interp = interp

    def __call__(self, src):
        if self.rotate > 0:
            angle = self.rotate
        else:
            angle = pyrandom.randint(-self.max_rotate_angle,
                                     self.max_rotate_angle)
        if angle == 0:
            return src
        return rotate_image(src, angle, self.fill_value, self.interp)


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        self.size, self.min_area, self.ratio, self.interp = \
            size, min_area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2, *,
                    max_rotate_angle=0, rotate=-1, fill_value=255,
                    random_h=0, random_s=0, random_l=0):
    """Build the standard augmenter list. Positional signature matches the
    reference (image.py:397 CreateAugmenter, through ``inter_method``); the
    native augmenter's geometric/color params from image_aug_default.cc
    (max_rotate_angle/rotate/fill_value, random_h/s/l) are keyword-only
    extensions so the Python path can mirror the C++ pipeline. Every
    accepted argument is honored — unknown needs should raise upstream,
    never be silently dropped."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    if max_rotate_angle > 0 or rotate > 0:
        # native order: affine rotation after resize, before crop
        auglist.append(RandomRotateAug(max_rotate_angle, rotate, fill_value))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop, "rand_resize requires rand_crop"
        auglist.append(RandomSizedCropAug(crop_size, 0.3,
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if random_h or random_s or random_l:
        # uint8 HLS-space jitter must precede the float cast (native order:
        # color-space aug after crop)
        auglist.append(HSLJitterAug(random_h, random_s, random_l))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise, PCA_EIGVAL, PCA_EIGVEC))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Flexible Python image iterator over .rec or image-list files
    (reference image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(self.data_shape, **kwargs)
        self.shuffle = shuffle
        self._rec = None
        self.imglist = []
        if path_imgrec:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = np.array(parts[1:1 + label_width], np.float32)
                    self.imglist.append((label, os.path.join(path_root,
                                                             parts[-1])))
        elif imglist:
            for label, fname in imglist:
                self.imglist.append((np.array(label, np.float32).reshape(-1),
                                     os.path.join(path_root, fname)))
        else:
            raise MXNetError("need path_imgrec, path_imglist, or imglist")
        self._order = list(range(len(self.imglist))) if self.imglist else None
        self._cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0
        if self._rec is not None:
            self._rec.reset()
        elif self.shuffle:
            pyrandom.shuffle(self._order)

    def next_sample(self):
        if self._rec is not None:
            buf = self._rec.read()
            if buf is None:
                raise StopIteration
            header, img = recordio.unpack(buf)
            lab = header.label
            return np.asarray(lab, np.float32).reshape(-1), img
        if self._cursor >= len(self.imglist):
            raise StopIteration
        label, fname = self.imglist[self._order[self._cursor]]
        self._cursor += 1
        with open(fname, "rb") as f:
            return label, f.read()

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.zeros((self.batch_size,), np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, buf = self.next_sample()
                img = imdecode(buf)
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = label.flat[0]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return DataBatch([nd.array(batch_data)], [nd.array(batch_label)],
                         pad=self.batch_size - i)


# --- detection augmenters (reference src/io/image_det_aug_default.cc,
# python ImageDetIter) -------------------------------------------------------
class DetAugmenter:
    """Augmenter over (image, boxes) pairs; boxes are (N, 5) arrays of
    [cls, xmin, ymin, xmax, ymax] normalized to [0, 1]."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a geometry-free classification augmenter (color jitter, cast)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = nd.array(np.ascontiguousarray(src.asnumpy()[:, ::-1]))
            label = label.copy()
            xmin = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - xmin
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Box-aware random crop with IoU/coverage constraint (the SSD
    "min_object_covered" sampler, image_det_aug_default.cc RandomCrop)."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=25):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ar = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(area * ar))
            ch = min(1.0, np.sqrt(area / ar))
            cx0 = pyrandom.uniform(0, 1 - cw)
            cy0 = pyrandom.uniform(0, 1 - ch)
            crop = np.array([cx0, cy0, cx0 + cw, cy0 + ch], np.float32)
            kept = self._crop_boxes(label, crop)
            if kept is None:
                continue
            x0, y0 = int(cx0 * w), int(cy0 * h)
            cw_px, ch_px = max(1, int(cw * w)), max(1, int(ch * h))
            img = src.asnumpy()[y0:y0 + ch_px, x0:x0 + cw_px]
            return nd.array(img), kept
        return src, label

    def _crop_boxes(self, label, crop):
        """Keep boxes whose center lies in the crop; require coverage."""
        if len(label) == 0:
            return label
        cx = (label[:, 1] + label[:, 3]) / 2
        cy = (label[:, 2] + label[:, 4]) / 2
        inside = ((cx >= crop[0]) & (cx <= crop[2])
                  & (cy >= crop[1]) & (cy <= crop[3]))
        if not inside.any():
            return None
        kept = label[inside].copy()
        # coverage check: clipped area / original area
        ow = kept[:, 3] - kept[:, 1]
        oh = kept[:, 4] - kept[:, 2]
        nx0 = np.maximum(kept[:, 1], crop[0])
        ny0 = np.maximum(kept[:, 2], crop[1])
        nx1 = np.minimum(kept[:, 3], crop[2])
        ny1 = np.minimum(kept[:, 4], crop[3])
        cover = (np.clip(nx1 - nx0, 0, None) * np.clip(ny1 - ny0, 0, None)
                 / np.clip(ow * oh, 1e-12, None))
        if cover.min() < self.min_object_covered:
            return None
        cw = crop[2] - crop[0]
        ch = crop[3] - crop[1]
        kept[:, 1] = np.clip((nx0 - crop[0]) / cw, 0, 1)
        kept[:, 2] = np.clip((ny0 - crop[1]) / ch, 0, 1)
        kept[:, 3] = np.clip((nx1 - crop[0]) / cw, 0, 1)
        kept[:, 4] = np.clip((ny1 - crop[1]) / ch, 0, 1)
        return kept


class DetRandomPadAug(DetAugmenter):
    """Zoom-out: place the image on a larger filled canvas and rescale
    boxes (image_det_aug_default.cc RandomPad)."""

    def __init__(self, max_expand_ratio=2.0, fill=(127, 127, 127), p=0.5):
        self.max_expand_ratio = max_expand_ratio
        self.fill = fill
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() >= self.p or self.max_expand_ratio <= 1.0:
            return src, label
        h, w = src.shape[:2]
        ratio = pyrandom.uniform(1.0, self.max_expand_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        y0 = pyrandom.randint(0, nh - h)
        x0 = pyrandom.randint(0, nw - w)
        canvas = np.empty((nh, nw, src.shape[2]), src.asnumpy().dtype)
        canvas[:] = np.asarray(self.fill, canvas.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = src.asnumpy()
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + x0) / nw
        label[:, 3] = (label[:, 3] * w + x0) / nw
        label[:, 2] = (label[:, 2] * h + y0) / nh
        label[:, 4] = (label[:, 4] * h + y0) / nh
        return nd.array(canvas), label


class DetResizeAug(DetAugmenter):
    """Force resize to (w, h); normalized boxes are unchanged."""

    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1], self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 1.0), max_expand_ratio=2.0,
                       pad_val=(127, 127, 127), inter_method=1):
    """Build the standard detection augmenter list (reference
    image_det_aug_default.cc CreateDetAugmenter)."""
    auglist: List[DetAugmenter] = []
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(max_expand_ratio, pad_val, rand_pad))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(min_object_covered,
                                        aspect_ratio_range, area_range))
    auglist.append(DetResizeAug((data_shape[2], data_shape[1]), inter_method))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness:
        auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


def parse_det_label(raw, object_width=5):
    """Decode a packed det label: either a flat multiple of object_width,
    or [header_width, object_width, ...header, objects...] (the reference
    det format, tools/im2rec packing). Returns (k, <=object_width)."""
    raw = np.asarray(raw, np.float32).reshape(-1)
    if len(raw) == 0:
        return np.zeros((0, object_width), np.float32)
    if len(raw) >= 2 and len(raw) % object_width != 0:
        hw, ow = int(raw[0]), int(raw[1])
        body = raw[hw:]
        return body.reshape(-1, ow)[:, :object_width].astype(np.float32)
    return raw.reshape(-1, object_width).astype(np.float32)


class ImageDetIter(ImageIter):
    """Detection iterator (reference ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc + python image.ImageDetIter): yields
    (data (B,C,H,W), label (B, max_objs, 5)) with -1 padding rows.

    Record label layout follows the reference det format: either a flat
    multiple of ``object_width`` (5), or ``[header_width, object_width,
    ...header, objects...]``."""

    def __init__(self, batch_size, data_shape, max_objs=16, aug_list=None,
                 **kwargs):
        self.max_objs = max_objs
        if aug_list is None:
            det_kwargs = {k: v for k, v in kwargs.items()
                          if k in ("resize", "rand_crop", "rand_pad",
                                   "rand_mirror", "mean", "std", "brightness",
                                   "min_object_covered", "aspect_ratio_range",
                                   "area_range", "max_expand_ratio",
                                   "pad_val", "inter_method")}
            aug_list = CreateDetAugmenter(data_shape, **det_kwargs)
            kwargs = {k: v for k, v in kwargs.items() if k not in det_kwargs}
        super().__init__(batch_size, data_shape, aug_list=[], **kwargs)
        self.det_auglist = aug_list

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self.max_objs, 5))]

    @staticmethod
    def _parse_label(raw):
        return parse_det_label(raw, 5)

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = -np.ones((self.batch_size, self.max_objs, 5), np.float32)
        i = 0
        try:
            while i < self.batch_size:
                raw_label, buf = self.next_sample()
                img = imdecode(buf)
                boxes = self._parse_label(raw_label)
                for aug in self.det_auglist:
                    img, boxes = aug(img, boxes)
                arr = img.asnumpy()
                batch_data[i] = arr.transpose(2, 0, 1)
                n = min(len(boxes), self.max_objs)
                if n:
                    batch_label[i, :n] = boxes[:n, :5]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return DataBatch([nd.array(batch_data)], [nd.array(batch_label)],
                         pad=self.batch_size - i)
