"""Deployment-only predictor (mx.predict).

TPU-native analogue of the reference's prediction C API
(include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc — SURVEY §2.1
#30) and the amalgamation predict-only build (MXNET_PREDICT_ONLY,
base.h:72-74). The reference loads a symbol JSON + param blob, binds a
reduced inference-only executor, and exposes
MXPredForward/GetOutput/Reshape. Here:

- ``Predictor`` loads the same artifacts our checkpoints write
  (``prefix-symbol.json`` + ``prefix-%04d.params``) and AOT-compiles ONE
  inference XLA computation for the given input shapes (the "bind reduced
  executor" step — no grads, no aux mutation, is_train=False).
- ``Predictor.export`` serializes the compiled computation with
  ``jax.export`` (StableHLO) next to the params — the amalgamation
  analogue: a self-contained artifact loadable by :func:`load` without the
  symbol/op registry.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import ndarray as nd
from . import progcache
from . import symbol as sym_mod
from .analysis import compile_witness as _witness
from .base import MXNetError
from .ndarray import NDArray

# Process-wide count of XLA inference compilations (every Predictor
# _compile). The serving bucket cache's steady-state contract — "no more
# compilations than configured buckets" — is asserted against this.
_COMPILE_COUNT = 0

# Process-wide count of programs loaded from the persistent progcache
# instead of compiled — the warm-restart counterpart of _COMPILE_COUNT.
_DISK_LOAD_COUNT = 0


def compile_count() -> int:
    """Number of Predictor XLA compilations in this process. With the
    compile witness armed (``MXNET_COMPILE_WITNESS=1``) this reads the
    witness ledger — one accounting source — covering both float and
    quantized predictors; otherwise the module counter."""
    if _witness.enabled():
        return (_witness.compiles_total("predictor")
                + _witness.compiles_total("quant"))
    return _COMPILE_COUNT


def disk_load_count() -> int:
    """Number of Predictor programs loaded from mxnet_tpu.progcache
    (witness ledger when armed, like :func:`compile_count`)."""
    if _witness.enabled():
        return (_witness.disk_loads_total("predictor")
                + _witness.disk_loads_total("quant"))
    return _DISK_LOAD_COUNT


class Predictor:
    """Inference-only executor (reference PredictorHandle)."""

    def __init__(self, symbol_json: str, params, input_shapes: Dict[str, tuple],
                 dtype="float32", device=None):
        """``symbol_json``: JSON string or path. ``params``: path to a
        ``.params`` file or a dict of name→array (both ``arg:``/``aux:``
        prefixed and bare names accepted, like MXPredCreate). ``device``:
        optional jax device to compile for and run on (serving replicas
        pin one executor per device; None = the default device)."""
        self._device = device
        if os.path.exists(symbol_json):
            self._symbol = sym_mod.load(symbol_json)
        else:
            self._symbol = sym_mod.load_json(symbol_json)

        if isinstance(params, str):
            loaded = nd.load(params)
        else:
            loaded = dict(params)
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            arr = v if isinstance(v, NDArray) else nd.array(v)
            if k.startswith("arg:"):
                arg_params[k[4:]] = arr
            elif k.startswith("aux:"):
                aux_params[k[4:]] = arr
            else:
                arg_params[k] = arr

        self._input_names = list(input_shapes)
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self._dtype = dtype
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        missing = [n for n in arg_names
                   if n not in arg_params and n not in self._input_shapes]
        if missing:
            # label-style args (SoftmaxOutput's label) are dead at inference;
            # bind them to zeros of the inferred shape rather than failing
            import logging

            shapes, _, _ = self._symbol.infer_shape(**self._input_shapes)
            inferred = dict(zip(arg_names, shapes))
            for n in missing:
                logging.getLogger("mxnet_tpu").debug(
                    "predictor: arg %r not in params; binding zeros %s",
                    n, inferred[n])
                arg_params[n] = nd.zeros(inferred[n], dtype=dtype)
        self._arg_params = {n: arg_params[n] for n in arg_names
                            if n in arg_params}
        self._aux_params = {n: aux_params[n] for n in aux_names
                            if n in aux_params}
        self._inputs: Dict[str, Optional[NDArray]] = {
            n: None for n in self._input_shapes}
        self._outputs: List[NDArray] = []
        # the run path (set_input/forward/get_output) mutates shared
        # instance state; the decode scheduler thread and user threads may
        # share one predictor, so serialize per instance (leaf lock, rank
        # 100 in analysis/lockorder.py — nothing is acquired under it)
        self._run_lock = threading.RLock()
        self._compile()

    def _compile(self):
        global _COMPILE_COUNT, _DISK_LOAD_COUNT
        eval_fn = self._symbol.build_eval()
        param_vals = {n: a._data for n, a in self._arg_params.items()}
        aux_vals = {n: a._data for n, a in self._aux_params.items()}
        input_names = self._input_names

        def fwd(*input_arrays):
            args = dict(param_vals)
            args.update(dict(zip(input_names, input_arrays)))
            outs, _ = eval_fn(args, aux_vals, False, jax.random.PRNGKey(0))
            return tuple(outs)

        self._jitted = jax.jit(fwd)
        # Persistent program cache: the key is computable from metadata
        # alone (symbol + param CRCs + input signature), so a warm hit
        # skips lower AND compile — that headroom is the ≥3× warm-restart
        # speedup. Param values are part of the model fingerprint because
        # they are closure constants baked into the serialized executable.
        cache_key = None
        if progcache.enabled():
            fp = getattr(self, "_progcache_model_fp", None)
            if fp is None:
                fp = progcache.model_fingerprint(
                    self._symbol, self._arg_params, self._aux_params)
            self._progcache_model_fp = fp
            cache_key = progcache.predictor_key(
                fp, input_names, self._input_shapes, self._dtype,
                self._device)
            loaded = progcache.load(cache_key, kind="predictor")
            if loaded is not None:
                self._lowered = None
                self._exec = loaded
                self.progcache_source = "disk"
                _DISK_LOAD_COUNT += 1
                return
        specs = [jax.ShapeDtypeStruct(self._input_shapes[n],
                                      jnp.dtype(self._dtype))
                 for n in input_names]
        # AOT compile now (MXPredCreate binds eagerly too)
        with self._device_scope():
            self._lowered = self._jitted.lower(*specs)
            self._exec = self._lowered.compile()
        _COMPILE_COUNT += 1
        _witness.record_compile(
            "predictor", key=cache_key or "",
            shapes=repr(sorted(self._input_shapes.items())))
        self.progcache_source = "compile"
        if cache_key is not None:
            progcache.store(cache_key, self._exec, note="predictor",
                            kind="predictor")

    def _device_scope(self):
        import contextlib

        return (jax.default_device(self._device) if self._device is not None
                else contextlib.nullcontext())

    # --- reference API surface -------------------------------------------
    def set_input(self, name: str, value):
        """MXPredSetInput."""
        if name not in self._inputs:
            raise MXNetError("unknown input %r (have %s)"
                             % (name, self._input_names))
        arr = value if isinstance(value, NDArray) else nd.array(value)
        if tuple(arr.shape) != self._input_shapes[name]:
            raise MXNetError("input %r shape %s != bound shape %s"
                             % (name, arr.shape, self._input_shapes[name]))
        with self._run_lock:
            self._inputs[name] = arr

    def forward(self, **inputs):
        """MXPredForward; inputs may also be passed as kwargs.

        Safe for concurrent callers: staged inputs are snapshotted and
        outputs published under the instance run lock, so two threads'
        calls can't clobber each other's state — each returns its own
        result list. The compiled call itself runs OUTSIDE the lock
        (XLA executables are safe to invoke concurrently), so callers
        overlap on the device instead of serializing."""
        with self._run_lock:
            for k, v in inputs.items():
                self.set_input(k, v)
            vals = []
            for n in self._input_names:
                if self._inputs[n] is None:
                    raise MXNetError("input %r not set" % n)
                vals.append(
                    self._inputs[n]._data.astype(jnp.dtype(self._dtype)))
        with self._device_scope():
            outs = self._exec(
                *[jax.device_put(v, self._device) for v in vals]
                if self._device is not None else vals)
        result = [NDArray(o) for o in outs]
        with self._run_lock:
            self._outputs = result
        return result

    def get_output(self, index: int) -> NDArray:
        """MXPredGetOutput."""
        with self._run_lock:
            return self._outputs[index]

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def reshape(self, new_input_shapes: Dict[str, tuple],
                device=None) -> "Predictor":
        """MXPredReshape: rebind with new shapes, sharing weights.
        ``device`` optionally re-pins the new executor (serving replicas);
        default inherits this predictor's device."""
        p = Predictor.__new__(Predictor)
        p._symbol = self._symbol
        p._arg_params = self._arg_params
        p._aux_params = self._aux_params
        p._input_names = list(new_input_shapes)
        p._input_shapes = {k: tuple(v) for k, v in new_input_shapes.items()}
        p._dtype = self._dtype
        p._device = device if device is not None else self._device
        p._inputs = {n: None for n in p._input_shapes}
        p._outputs = []
        p._run_lock = threading.RLock()  # __new__ bypasses __init__
        # params are shared by reference, so the model fingerprint (which
        # hashes their bytes) is shared too — a full-ladder warm() hashes
        # the weights once, not once per bucket
        fp = getattr(self, "_progcache_model_fp", None)
        if fp is not None:
            p._progcache_model_fp = fp
        p._compile()
        return p

    def quantize(self, weight_dtype: str = "int8", act_dtype: str = "int8"):
        """Post-training quantization: a :class:`~mxnet_tpu.quant.
        QuantizedPredictor` over the same symbol and weights, with every
        eligible FC/conv weight stored per-channel ``weight_dtype``
        (int8 / fp8_e4m3) and scales passed as extra program arguments —
        the progcache key stays weight-independent. The original
        predictor is untouched."""
        from . import quant as _quant

        return _quant.quantize_predictor(
            self, _quant.QuantConfig(weight_dtype=weight_dtype,
                                     act_dtype=act_dtype))

    # --- serialized-executable export (amalgamation analogue) -------------
    def export(self, path: str):
        """Write a self-contained artifact: serialized StableHLO executable
        (jax.export) + params + metadata. Loadable by :func:`load` with no
        symbol/op registry needed — the deployment story of the reference's
        amalgamation single-file build."""
        from jax import export as jax_export

        os.makedirs(path, exist_ok=True)
        specs = [jax.ShapeDtypeStruct(self._input_shapes[n],
                                      jnp.dtype(self._dtype))
                 for n in self._input_names]
        exported = jax_export.export(self._jitted)(*specs)
        with open(os.path.join(path, "model.stablehlo"), "wb") as f:
            f.write(exported.serialize())
        meta = {
            "input_names": self._input_names,
            "input_shapes": {k: list(v) for k, v in self._input_shapes.items()},
            "dtype": self._dtype,
            "output_names": self.output_names,
        }
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
        nd.save(os.path.join(path, "model.params"),
                {"arg:%s" % k: v for k, v in self._arg_params.items()} |
                {"aux:%s" % k: v for k, v in self._aux_params.items()})
        # symbol JSON too, so the artifact can also be rebound if desired
        self._symbol.save(os.path.join(path, "model-symbol.json"))


class ExportedPredictor:
    """Runs a serialized StableHLO artifact written by Predictor.export."""

    def __init__(self, path: str):
        from jax import export as jax_export

        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        with open(os.path.join(path, "model.stablehlo"), "rb") as f:
            self._exported = jax_export.deserialize(bytearray(f.read()))
        self._input_names = meta["input_names"]
        self._input_shapes = {k: tuple(v)
                              for k, v in meta["input_shapes"].items()}
        self._dtype = meta["dtype"]
        self._output_names = meta["output_names"]
        self._outputs: List[NDArray] = []

    def forward(self, **inputs):
        vals = []
        for n in self._input_names:
            if n not in inputs:
                raise MXNetError("input %r not provided" % n)
            v = inputs[n]
            arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            if tuple(arr.shape) != self._input_shapes[n]:
                raise MXNetError(
                    "input %r shape %s != exported shape %s"
                    % (n, tuple(arr.shape), self._input_shapes[n]))
            vals.append(arr.astype(jnp.dtype(self._dtype)))
        outs = self._exported.call(*vals)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        self._outputs = [NDArray(o) for o in outs]
        return self._outputs

    def get_output(self, index: int) -> NDArray:
        return self._outputs[index]

    @property
    def output_names(self):
        return self._output_names


def load(path: str) -> ExportedPredictor:
    return ExportedPredictor(path)


def create(prefix: str, epoch: int, input_shapes: Dict[str, tuple],
           dtype="float32") -> Predictor:
    """Build a Predictor straight from a training checkpoint pair
    (``prefix-symbol.json`` + ``prefix-%04d.params``)."""
    return Predictor("%s-symbol.json" % prefix,
                     "%s-%04d.params" % (prefix, epoch), input_shapes, dtype)
