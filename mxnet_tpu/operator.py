"""Custom operators written in Python.

TPU-native redesign of python/mxnet/operator.py (CustomOp/CustomOpProp +
``register``, operator.py:396-576) and the native callback bridge
src/operator/custom/custom.cc (SURVEY §2.1 #20).

The reference routes custom-op calls from the engine's async path through C
function pointers back into Python, copying TBlobs into NDArrays
(custom.cc:39-60ff). Here the equivalent escape hatch out of the compiled
XLA graph is ``jax.pure_callback``: the op's forward/backward run as host
callbacks on numpy-backed NDArrays, while the surrounding graph stays
jit-compiled. Gradients are wired with ``jax.custom_vjp`` so a Custom op
composes with autodiff exactly like a built-in (the reference achieves this
by registering a synthetic backward node, custom.cc + legacy_op_util.cc).

User API (identical shape to the reference):

    @mx.operator.register("softmax")
    class SoftmaxProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)
        def list_arguments(self): return ['data', 'label']
        def list_outputs(self):   return ['output']
        def infer_shape(self, in_shape): ...
        def create_operator(self, ctx, shapes, dtypes): return Softmax()

    out = mx.nd.Custom(data, label, op_type='softmax')
    s   = mx.sym.Custom(data=d, label=l, op_type='softmax')
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError

# op_type -> CustomOpProp subclass (reference CustomOpProp::registry_,
# custom.cc:13)
_CUSTOM_OP_REGISTRY: Dict[str, type] = {}


class CustomOp:
    """Base class for custom operator implementations (reference
    operator.py:396 ``CustomOp``). Subclass and override forward/backward."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring OpReqType semantics
        (operator.h:24-37: null/write/inplace/add)."""
        if req in ("null", 0):
            return
        if req in ("write", "inplace", 1, 2):
            dst[:] = src
        elif req in ("add", 3):
            dst[:] += src
        else:
            raise MXNetError("unknown req %r" % (req,))


class CustomOpProp:
    """Metadata class (reference operator.py ``CustomOpProp``; the analogue
    of OperatorProperty, operator.h:166-480)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        """Default: all outputs take the first input's shape."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name: str):
    """Register a CustomOpProp subclass under ``op_type=reg_name``
    (reference operator.py:576 ``register`` via MXCustomOpRegister)."""

    def dec(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("custom op %r must subclass CustomOpProp" % reg_name)
        _CUSTOM_OP_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return dec


def get_prop_cls(op_type: str) -> type:
    try:
        return _CUSTOM_OP_REGISTRY[op_type]
    except KeyError:
        raise MXNetError(
            "custom op type %r is not registered (known: %s)"
            % (op_type, sorted(_CUSTOM_OP_REGISTRY))
        ) from None


def make_prop(attrs: Dict[str, Any]) -> CustomOpProp:
    """Instantiate the prop from Custom-op attrs. Non-``op_type`` attrs are
    forwarded to the prop constructor as strings, matching the reference's
    kwarg marshalling through the C bridge (custom.cc keyword char**)."""
    kwargs = {k: str(v) for k, v in attrs.items() if k != "op_type"}
    return get_prop_cls(str(attrs["op_type"]))(**kwargs)


class _HostTensor:
    """Mutable host-side tensor handed to CustomOp.forward/backward.

    Behaves like the NDArray surface custom ops actually use: numpy in,
    numpy out, in-place slice assignment (the reference copies engine TBlobs
    into temporary NDArrays the same way, custom.cc:39-60)."""

    __slots__ = ("_np",)

    def __init__(self, arr: np.ndarray):
        self._np = arr

    def asnumpy(self) -> np.ndarray:
        return self._np

    @property
    def shape(self):
        return self._np.shape

    @property
    def dtype(self):
        return self._np.dtype

    def __getitem__(self, idx):
        return self._np[idx]

    def __setitem__(self, idx, val):
        self._np[idx] = np.asarray(
            val.asnumpy() if hasattr(val, "asnumpy") else val, self._np.dtype
        )

    def __array__(self, dtype=None):
        return self._np if dtype is None else self._np.astype(dtype)


def _result_specs(shapes, dtypes):
    return tuple(jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                 for s, d in zip(shapes, dtypes))


def apply_custom(attrs: Dict[str, Any], inputs, aux, is_train: bool):
    """Execute a Custom op inside a traced/jitted graph.

    Returns (outputs tuple, aux updates tuple). Forward and backward each
    lower to one ``pure_callback`` into the user's Python code; ``custom_vjp``
    splices the backward callback into the autodiff graph.
    """
    prop = make_prop(attrs)
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    if len(aux) != n_aux:
        raise MXNetError(
            "Custom(%s): expected %d aux states, got %d"
            % (attrs.get("op_type"), n_aux, len(aux))
        )

    in_shapes = [tuple(x.shape) for x in inputs]
    _, out_shapes, aux_shapes = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [x.dtype for x in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    out_specs = _result_specs(out_shapes, out_types)
    aux_specs = _result_specs([a.shape for a in aux], [a.dtype for a in aux])

    op_holder: List[Optional[CustomOp]] = [None]
    op_lock = threading.Lock()

    def get_op():
        # fwd_cb and bwd_cb share this memoization from pure_callback, and
        # the runtime may replay them concurrently — without the lock two
        # replays can race create_operator and train two distinct stateful
        # op instances
        with op_lock:
            if op_holder[0] is None:
                op_holder[0] = prop.create_operator(
                    None, [list(s) for s in in_shapes], in_types
                )
            return op_holder[0]

    n_in = len(inputs)

    def fwd_cb(*arrays):
        ins = [_HostTensor(np.asarray(a).copy()) for a in arrays[:n_in]]
        auxs = [_HostTensor(np.asarray(a).copy()) for a in arrays[n_in:]]
        outs = [_HostTensor(np.zeros(s.shape, s.dtype)) for s in out_specs]
        get_op().forward(is_train, ["write"] * n_out, ins, outs, auxs)
        return tuple(o.asnumpy().astype(s.dtype) for o, s in zip(outs, out_specs)) + tuple(
            a.asnumpy().astype(sp.dtype) for a, sp in zip(auxs, aux_specs)
        )

    def bwd_cb(*arrays):
        # layout: inputs, outputs, aux, out_grads
        ofs = 0
        ins = [_HostTensor(np.asarray(a).copy()) for a in arrays[ofs:ofs + n_in]]
        ofs += n_in
        outs = [_HostTensor(np.asarray(a).copy()) for a in arrays[ofs:ofs + n_out]]
        ofs += n_out
        auxs = [_HostTensor(np.asarray(a).copy()) for a in arrays[ofs:ofs + n_aux]]
        ofs += n_aux
        ograds = [_HostTensor(np.asarray(a).copy()) for a in arrays[ofs:]]
        igrads = [_HostTensor(np.zeros(s, np.dtype(d)))
                  for s, d in zip(in_shapes, in_types)]
        get_op().backward(["write"] * n_in, ograds, ins, outs, igrads, auxs)
        return tuple(g.asnumpy().astype(d) for g, d in zip(igrads, in_types))

    in_specs = _result_specs(in_shapes, in_types)

    @jax.custom_vjp
    def run(*ins):
        res = jax.pure_callback(fwd_cb, out_specs + aux_specs, *ins, *aux)
        return tuple(res)

    def run_fwd(*ins):
        res = run(*ins)
        return res, (ins, res[:n_out])

    def run_bwd(residuals, cotangents):
        ins, outs = residuals
        ograds = cotangents[:n_out]
        igrads = jax.pure_callback(
            bwd_cb, in_specs, *ins, *outs, *aux, *ograds
        )
        return tuple(igrads)

    run.defvjp(run_fwd, run_bwd)
    res = run(*inputs)
    return tuple(res[:n_out]), tuple(res[n_out:])


# --- legacy interfaces (reference NDArrayOp/NumpyOp, operator.py:28-390) ----
class PythonOp(CustomOp):
    """Legacy-style numpy op base (reference NumpyOp). Implement
    ``forward(in_data, out_data)`` / ``backward(out_grad, in_data, out_data,
    in_grad)`` over numpy arrays; adapted onto the CustomOp interface."""

    def forward(self, is_train, req, in_data, out_data, aux):  # noqa: D102
        self.forward_np([x.asnumpy() for x in in_data],
                        [x.asnumpy() for x in out_data])
        # forward_np mutates the out numpy arrays in place via _HostTensor
        for o in out_data:
            self.assign(o, req[0] if req else "write", o.asnumpy())

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.backward_np([x.asnumpy() for x in out_grad],
                         [x.asnumpy() for x in in_data],
                         [x.asnumpy() for x in out_data],
                         [x.asnumpy() for x in in_grad])

    def forward_np(self, in_data, out_data):
        raise NotImplementedError

    def backward_np(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError
