"""Weight initializers.

Reimplementation of python/mxnet/initializer.py (device-agnostic layer in
the reference, SURVEY §2.4): name-pattern dispatch, the full initializer
zoo (Zero/One/Constant/Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/
LSTMBias), InitDesc and Mixed.
"""
from __future__ import annotations

import json
import re

import numpy as np

from . import ndarray as nd
from .base import MXNetError

init_registry = {}


def register(klass):
    init_registry[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor (reference initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            inst = init_registry[klass.lower()](**kwargs)
            # full suffix dispatch of the attr-selected initializer (a
            # 'parameters' blob must hit its _init_parameters, not
            # _init_weight); strip the attr to avoid recursion
            clean = InitDesc(str(desc),
                             {k: v for k, v in desc.attrs.items()
                              if k != "__init__"}, desc.global_init)
            inst(clean, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("parameters"):
            self._init_parameters(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_bilinear(self, _, arr):
        weight = np.zeros(arr.shape, dtype="float32").reshape(-1)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_parameters(self, name, arr):
        """Packed fused-RNN blobs ('..._parameters'). Generic initializers
        fall back to a small uniform fill (shape-dependent rules like
        Xavier cannot see the per-matrix structure of a flat blob); use
        initializer.FusedRNN for per-matrix init + forget-bias semantics."""
        arr[:] = np.random.uniform(-0.07, 0.07, arr.shape).astype(
            "float32")

    def _init_default(self, name, arr):
        raise MXNetError(
            "Unknown initialization pattern for %s. " % name
            + "Default initialization is now limited to "
            '"weight", "bias", "gamma" (1.0), and "beta" (0.0).'
        )


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape).astype(np.float32)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(np.float32)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * res).reshape(arr.shape).astype(np.float32)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape).astype(np.float32)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, shape).astype(np.float32)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        Initializer._init_bilinear(self, _, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_bias(self, _, arr):
        # bias-suffixed names dispatch here, not to _init_weight
        v = np.zeros(arr.shape, np.float32)
        num_hidden = int(arr.shape[0] / 4)
        v[num_hidden : 2 * num_hidden] = self.forget_bias
        arr[:] = v

    _init_weight = _init_bias  # tolerate non-_bias-suffixed param names


@register
class FusedRNN(Initializer):
    """Initializer for fused RNN packed parameter blobs."""

    def __init__(self, init=None, num_hidden=0, num_layers=0, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = init_registry[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init else None, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        # non-blob weights (mixed nets initialized wholesale with
        # FusedRNN): delegate to the inner init
        if self._init is not None:
            self._init._init_weight(desc, arr)
        else:
            arr[:] = np.random.uniform(-0.07, 0.07, arr.shape).astype(
                "float32")

    def _init_parameters(self, desc, arr):
        """Per-matrix initialization of the packed blob (the reference
        unpacks, applies the inner init per weight matrix, then repacks).
        Packed layout (ops/rnn_fused.py rnn_param_size/_unpack_params):
        per layer per direction wi then wh, then ALL biases (bi, bh per
        layer/dir, each gates*h; gate order i,f,g,o)."""
        kw = self._kwargs
        h = int(kw.get("num_hidden") or 0)
        layers = int(kw.get("num_layers") or 0)
        mode = kw.get("mode", "lstm")
        dirs = 2 if kw.get("bidirectional") else 1
        gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}.get(
            mode, 0)
        total = int(np.prod(arr.shape))
        bias_total = layers * dirs * gates * h * 2

        def fill(mat_shape, name):
            out = np.empty(mat_shape, np.float32)
            if self._init is not None:
                from . import ndarray as nd

                buf = nd.zeros(mat_shape)
                self._init._init_weight(InitDesc(name), buf)
                out[:] = buf.asnumpy()
            else:
                out[:] = np.random.uniform(-0.07, 0.07, mat_shape)
            return out

        if not (h and layers and gates and bias_total < total):
            # Unknown layout: shape-INdependent inner inits (Uniform/Normal/
            # Constant) still apply fine to the flat blob; shape-dependent
            # ones (Xavier/Orthogonal/Bilinear) assume >=2 dims and would
            # raise or produce degenerate scales on (total,), so those fall
            # back to the plain uniform fill instead.
            if isinstance(self._init, (Uniform, Normal, Constant, Zero, One)):
                arr[:] = fill((total,), str(desc)).reshape(arr.shape)
            else:
                arr[:] = np.random.uniform(-0.07, 0.07,
                                           (total,)).reshape(arr.shape)
            return
        # recover the input size from the blob length
        w_total = total - bias_total
        per_upper = dirs * gates * h * (dirs * h + h)  # layers > 0
        ni = (w_total - (layers - 1) * per_upper) // (dirs * gates * h) - h
        v = np.empty(total, np.float32)
        p = 0
        for layer in range(layers):
            in_sz = ni if layer == 0 else h * dirs
            for d in range(dirs):
                n_wi = gates * h * in_sz
                v[p:p + n_wi] = fill((gates * h, in_sz),
                                     "%s_l%d_wi" % (desc, layer)).reshape(-1)
                p += n_wi
                n_wh = gates * h * h
                v[p:p + n_wh] = fill((gates * h, h),
                                     "%s_l%d_wh" % (desc, layer)).reshape(-1)
                p += n_wh
        # biases zeroed; LSTM forget-gate slice of each bi = forget_bias
        biases = np.zeros((2 * layers * dirs, gates * h), np.float32)
        if mode == "lstm" and self.forget_bias:
            biases[0::2, h:2 * h] = self.forget_bias  # bi rows only
        v[p:] = biases.reshape(-1)
        arr[:] = v.reshape(arr.shape)


class Mixed:
    """Pattern-matched initializer dispatch (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern" % name)


class Load:
    """Initialize from a saved param dict (reference initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {
            k[4:] if k.startswith("arg:") or k.startswith("aux:") else k: v
            for k, v in param.items()
        }
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise ValueError("Parameter %s shape mismatch" % name)
            arr[:] = self.param[name]._data
        else:
            if self.default_init is None:
                raise ValueError("Cannot init %s: not found and no default" % name)
            self.default_init(name, arr)
