"""Module — the concrete training module.

Reimplementation of python/mxnet/module/module.py (SURVEY §2.4): bind builds
the (sharded) executor group, init_optimizer selects kvstore placement and
rescale_grad (module.py:432-511), update dispatches to kvstore or local
updater (module.py:553-570), checkpoints include optimizer state
(module.py:135, 674-704).
"""
from __future__ import annotations

import logging

import numpy as np

import jax.numpy as jnp

from .. import ndarray as nd
from .. import optimizer as opt
from ..optimizer import (Optimizer, cached_lr_wd_arrays, state_leaves,
                         write_state_leaves)
from ..base import MXNetError
from ..context import Context, cpu
from ..initializer import InitDesc, Uniform
from ..model import (
    BatchEndParam, _create_kvstore, _initialize_kvstore,
    _update_params, _update_params_on_kvstore, load_checkpoint,
    save_checkpoint,
)
from ..parallel import collectives as _collectives
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 compute_dtype=None):
        """compute_dtype: mixed-precision compute dtype for the bound
        executors ("bfloat16"; master weights stay fp32) — the Module-level
        surface of Executor's compute_dtype / MXNET_COMPUTE_DTYPE."""
        super().__init__(logger=logger)
        if context is None:
            context = [cpu()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._compute_dtype = compute_dtype

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) if fixed_param_names is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None

        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused_fit = None      # lazy fused fit-step state
        self._fused_dirty = False   # fused params newer than exec buffers
        self._fused_refresh = False  # exec buffers newer than fused snapshot
        self._monitor_installed = False

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """(reference module.py:115 Module.load)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        async_write=False):
        """(reference module.py:135). ``async_write=True`` overlaps the
        blob writes with continued training (engine-ordered; see
        engine.push_file_write)."""
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name, async_write=async_write)
        logging.info("Saved checkpoint to \"%s\"", param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name,
                                       async_write=async_write)
            logging.info("Saved optimizer state to \"%s\"", state_name)

    # --- properties -------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in
                zip(self._output_names, self._exec_group.get_outputs())] \
            if self._exec_group._exec.outputs else None

    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        """(reference module.py:237)."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        # a fused fit-step threads (donated) parameter buffers of its own;
        # materialize them into the exec buffers, then mark the snapshot
        # stale so explicitly-set parameters take effect on the next step
        # (the compiled step program is kept — no per-epoch recompile)
        self._sync_fused_to_exec()
        fs = self._fused_fit
        if isinstance(fs, dict) and fs.get("capture") is not None:
            fs["capture"].invalidate("param-set")
        self._fused_refresh = True

        if self._arg_params is None:
            self._arg_params = {
                n: nd.zeros(a.shape)
                for n, a in self._exec_group._exec.arg_dict.items()
                if n in self._param_names
            }
        if self._aux_params is None:
            self._aux_params = {
                n: nd.zeros(a.shape)
                for n, a in self._exec_group._exec.aux_dict.items()
            }

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        if cache_arr.shape != arr.shape:
                            raise MXNetError(
                                "shape mismatch for %s: %s vs %s"
                                % (name, cache_arr.shape, arr.shape)
                            )
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(InitDesc(name, attrs.get(name)), arr)
            else:
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name)), arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(reference module.py:323: builds DataParallelExecutorGroup)."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        from ..io import DataDesc

        self._data_shapes = [
            x if hasattr(x, "name") else DataDesc(*x) for x in data_shapes
        ]
        if label_shapes is not None:
            self._label_shapes = [
                x if hasattr(x, "name") else DataDesc(*x) for x in label_shapes
            ]
        else:
            self._label_shapes = None

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, self._data_shapes,
            self._label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names, compute_dtype=self._compute_dtype,
        )
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._close_fused_capture("rebind")
        if self._fused_fit:
            # force_rebind discards the fused state: flush its deferred
            # lockstep counts first or _index_update_count permanently
            # lags num_update (save/resume would serialize wrong t)
            self._materialize_fused_counts(self._fused_fit)
        self._fused_fit = None
        self._fused_dirty = False
        self._fused_refresh = False

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """(reference module.py:432: kvstore selection + rescale_grad)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params
        )

        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._exec_group.param_names))
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            _initialize_kvstore(
                kvstore=kvstore, param_arrays=self._exec_group.param_arrays,
                arg_params=self._arg_params, param_names=self._param_names,
                update_on_kvstore=update_on_kvstore,
            )
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        self._sync_fused_to_exec()
        self._close_fused_capture("optimizer re-init")
        self._fused_fit = None  # re-evaluate fused eligibility
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """Share optimizer/updater state with another Module (reference
        module.py borrow_optimizer, used by BucketingModule)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # --- computations -----------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._sync_fused_to_exec()
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._sync_fused_to_exec()
        self._exec_group.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused path: one jitted XLA computation per step."""
        assert self.binded and self.params_initialized
        self._sync_fused_to_exec()
        self._exec_group.forward_backward(data_batch)

    def update(self):
        """(reference module.py:553; model.py:88-110 update paths)."""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        # the manual path mutates exec/updater buffers directly: retire the
        # fused snapshot (its compiled step is kept; fit_step re-snapshots)
        self._sync_fused_to_exec()
        self._fused_refresh = True
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(
                self._exec_group.param_arrays, self._exec_group.grad_arrays,
                self._kvstore,
            )
        else:
            _update_params(
                self._exec_group.param_arrays, self._exec_group.grad_arrays,
                updater=self._updater, num_device=len(self._context),
                kvstore=self._kvstore,
            )

    def update_metric(self, eval_metric, labels):
        self._capture_fence()  # outputs are set on an engine worker
        self._exec_group.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        self._capture_fence()  # outputs are set on an engine worker
        return self._exec_group.get_outputs(merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context=merge_multi_context)

    def _sync_params_from_devices(self):
        self._sync_fused_to_exec()
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def save_optimizer_states(self, fname, async_write=False):
        assert self.optimizer_initialized
        self._sync_fused_to_exec()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            from .. import engine

            blob = self._updater.get_states()  # snapshot at call time

            def write():
                # atomic: tmp + os.replace (crash-safe like save_params)
                import os as _os

                with open(fname + ".tmp", "wb") as fout:
                    fout.write(blob)
                _os.replace(fname + ".tmp", fname)

            engine.push_file_write(fname, write, wait=not async_write,
                                   name="save_optimizer_states")

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        self._sync_fused_to_exec()  # keep fused params; pre-load states moot
        self._close_fused_capture("optimizer state load")
        self._fused_fit = None      # rebuild so loaded states are picked up
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            from .. import engine

            engine.wait_for_file(fname)
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    # --- resumable training state (mxnet_tpu.resilience) ------------------
    def get_checkpoint_state(self):
        """Everything a resumed job needs, as host arrays: f32 master
        params (``param:<name>``), aux states (``aux:<name>``), optimizer
        state leaves (``opt:<name>:<leaf>``), plus an ``opt_meta`` dict
        with the update counts. The flat dict feeds
        ``resilience.checkpoint.save_sharded`` directly; the snapshot is
        consistent (fused/donated buffers are synced out first).

        When the fused ZeRO state is live, the snapshot reads host copies
        straight off the 1/N device shards (``np.asarray`` assembles the
        flat value shard-by-shard on the host — the contiguous layout
        matches ``checkpoint._shard_range``'s divmod plan, so the write
        stays local). The pre-fix path went through ``get_params``, whose
        fused→exec sync ``replicate_place``s every master leaf — committing
        a FULL replicated copy of params + optimizer state to every device
        just to checkpoint them. The fused snapshot stays authoritative;
        exec buffers are not touched."""
        assert self.binded and self.params_initialized
        fs = self._fused_fit if isinstance(self._fused_fit, dict) else None
        if fs is not None and fs.get("z1") and self.optimizer_initialized:
            return self._sharded_checkpoint_state(fs)
        arg_params, aux_params = self.get_params()  # syncs fused → exec
        arrays = {}
        for n, a in arg_params.items():
            arrays["param:%s" % n] = a.asnumpy()
        for n, a in aux_params.items():
            arrays["aux:%s" % n] = a.asnumpy()
        opt_meta = {}
        if self.optimizer_initialized and self._updater is not None:
            nd_dev = len(self._context)
            for pos, n in enumerate(self._exec_group.param_names):
                leaves = state_leaves(
                    self._updater.states.get(pos * nd_dev))
                if leaves is None:
                    continue
                if not isinstance(leaves, tuple):
                    leaves = (leaves,)
                for li, leaf in enumerate(leaves):
                    if leaf is not None:
                        arrays["opt:%s:%d" % (n, li)] = np.asarray(leaf)
            opt_ = self._optimizer
            opt_meta = {
                "num_update": int(opt_.num_update),
                "index_update_count": {
                    str(k): int(v)
                    for k, v in opt_._index_update_count.items()},
            }
        return arrays, opt_meta

    def _sharded_checkpoint_state(self, fs):
        """ZeRO local-write snapshot: host arrays from the live fused
        1/N-sharded params/optimizer state, without replicating anything
        on device (see :meth:`get_checkpoint_state`)."""
        cap = fs.get("capture")
        if cap is not None:  # in-flight replayed steps finish first
            cap.fence()
        self._materialize_fused_counts(fs)
        arrays = {}
        for n in fs["names"]:
            arrays["param:%s" % n] = np.asarray(fs["params"][n])
            leaves = fs["states"][n]
            if leaves is None:
                continue
            if not isinstance(leaves, tuple):
                leaves = (leaves,)
            for li, leaf in enumerate(leaves):
                if leaf is not None:
                    arrays["opt:%s:%d" % (n, li)] = np.asarray(leaf)
        for n, a in self._exec_group._exec.aux_dict.items():
            arrays["aux:%s" % n] = a.asnumpy()
        opt_ = self._optimizer
        opt_meta = {
            "num_update": int(opt_.num_update),
            "index_update_count": {
                str(k): int(v)
                for k, v in opt_._index_update_count.items()},
        }
        return arrays, opt_meta

    def restore_checkpoint_state(self, arrays, opt_meta=None):
        """Inverse of :meth:`get_checkpoint_state`: install params, aux,
        optimizer-state leaves and update counts from a (possibly
        resharded) ``resilience.checkpoint`` restore. The fused step
        state is retired so the next ``fit_step`` re-snapshots from the
        restored buffers."""
        assert self.binded and self.params_initialized
        arg_params, aux_params, opt_leaves = {}, {}, {}
        for key, a in arrays.items():
            kind, _, rest = key.partition(":")
            if kind == "param":
                arg_params[rest] = nd.array(a)
            elif kind == "aux":
                aux_params[rest] = nd.array(a)
            elif kind == "opt":
                name, _, li = rest.rpartition(":")
                opt_leaves.setdefault(name, {})[int(li)] = a
            else:
                raise MXNetError("unknown checkpoint key %r" % key)
        self.set_params(arg_params, aux_params,
                        allow_missing=not arg_params)
        if not (self.optimizer_initialized and self._updater is not None):
            return
        self._sync_fused_to_exec()
        self._close_fused_capture("checkpoint restore")
        self._fused_fit = None  # re-snapshot from the restored buffers
        nd_dev = len(self._context)
        exec_ = self._exec_group._exec
        hyper_key = self._optimizer._hyperparam_key()
        for pos, n in enumerate(self._exec_group.param_names):
            entry = opt_leaves.get(n)
            if not entry:
                continue
            st = self._updater.ensure_state(pos * nd_dev,
                                            exec_.arg_dict[n],
                                            key=hyper_key)
            cur = state_leaves(st)
            if isinstance(cur, tuple):
                vals = tuple(
                    None if c is None else jnp.asarray(
                        entry[i]).astype(c.dtype)
                    for i, c in enumerate(cur))
            else:
                vals = jnp.asarray(entry[0]).astype(cur.dtype)
            write_state_leaves(st, vals)
        if opt_meta:
            opt_ = self._optimizer
            opt_.num_update = int(opt_meta.get("num_update",
                                               opt_.num_update))
            opt_._index_update_count = {
                int(k): int(v)
                for k, v in opt_meta.get("index_update_count",
                                         {}).items()}

    # --- fused fit step ---------------------------------------------------
    def fit_step(self, data_batch):
        """ONE donated XLA program per training step (fwd + bwd + optimizer;
        Executor.make_train_step) when the setup allows it — the whole-step
        analogue of the reference's bulk segments + fused optimizer kernels
        (graph_executor.cc:681-759, optimizer_op.cc). Parameters and
        optimizer state are threaded functionally through donated buffers;
        exec/arg_params buffers are refreshed lazily on get_params/eval.
        Falls back to forward_backward + update otherwise."""
        fs = self._fused_fit_state()
        if fs is not None and fs["hyper"] != self._optimizer._hyperparam_key():
            # a baked-in hyperparameter (momentum/beta warmup schedule)
            # mutated mid-training: the compiled step traced the old value —
            # sync state out and rebuild (same contract as Updater.update_all)
            self._sync_fused_to_exec()
            self._close_fused_capture("hyperparameter change")
            self._fused_fit = None
            fs = self._fused_fit_state()
        if fs is None:
            self.forward_backward(data_batch)
            self.update()
            return
        if self._fused_refresh:
            self._refresh_fused_snapshot(fs)
        opt_ = self._optimizer
        idx_of = fs["idx_of"]
        # constant-lr fast path: when the optimizer uses the BASE
        # effective_lr_wd (not a count-dependent override like Adam's
        # bias correction) and has no scheduler, per-param lr/wd only
        # move via optimizer.lr/.wd or the mult setters (which bump
        # _mult_version) — skip the 2x n_params effective_lr_wd rebuild
        # AND the per-param count loop (~1 ms/step combined on
        # ResNet-50). Counts advance in LOCKSTEP in the fused path, so a
        # single pending counter materializes into _index_update_count
        # whenever the fused state is left (_sync_fused_to_exec) or the
        # slow path below needs exact per-index t.
        static_lw = (opt_.lr_scheduler is None
                     and type(opt_).effective_lr_wd
                     is Optimizer.effective_lr_wd)
        if static_lw:
            fs["pending_counts"] = fs.get("pending_counts", 0) + 1
            opt_.num_update += 1
        else:
            self._materialize_fused_counts(fs)
            for n in fs["names"]:
                opt_._update_count(idx_of[n])
        # fingerprint also keys on the mult dicts' identity/size so a
        # reassignment (opt.lr_mult = {...}) or addition is seen even
        # without the setters; in-place VALUE mutation of an existing
        # entry requires set_lr_mult/set_wd_mult (documented there)
        fp = (None if not static_lw
              else (opt_.lr, opt_.wd, opt_._mult_version,
                    id(opt_.lr_mult), len(opt_.lr_mult),
                    id(opt_.wd_mult), len(opt_.wd_mult)))
        if fp is None or fs.get("lw_fp") != fp or "lw" not in fs:
            lw = np.array([opt_.effective_lr_wd(idx_of[n])
                           for n in fs["names"]], np.float32)
            # lr/wd arrays cached across steps (constant-lr: no re-upload);
            # committed replicated over the data mesh under ZeRO-1 so the
            # sharded step isn't fed single-device arrays
            lw_sh = None
            if fs.get("z1"):
                from jax.sharding import NamedSharding, PartitionSpec
                lw_sh = NamedSharding(fs["mesh"], PartitionSpec())
            _, _, fs["lw"] = cached_lr_wd_arrays(fs.get("lw"), lw,
                                                 sharding=lw_sh)
            fs["lw_fp"] = fp
        lr_arr, wd_arr = fs["lw"][1], fs["lw"][2]
        cap = self._fit_capture(fs, data_batch)
        if cap is not None:
            # engine capture/replay (MXNET_ENGINE_CAPTURE): the two host
            # ops of a steady-state step ride a CapturedSequence — eager
            # for the warmup steps, then ONE engine submission per step.
            # The closures read fs at RUN time, so each replayed step
            # consumes the params/states its predecessor threaded through.
            exec_group = self._exec_group

            def load(_db=data_batch):
                exec_group._load_data(_db)

            def stepped(_lr=lr_arr, _wd=wd_arr):
                _, fs["params"], fs["states"] = fs["step"](
                    fs["params"], fs["states"], {}, _lr, _wd)

            f_load, f_step = self._fit_fuse_ops(fs, cap, data_batch,
                                                lr_arr, wd_arr)
            cap.step(load, stepped, fuse_load=f_load, fuse_step=f_step)
        else:
            # place the batch with the group's device/sharding logic; the
            # step then reads the executor's data buffers (empty feed dict).
            self._exec_group._load_data(data_batch)
            _, fs["params"], fs["states"] = fs["step"](
                fs["params"], fs["states"], {}, lr_arr, wd_arr)
        self._params_dirty = True
        self._fused_dirty = True

    def _fit_capture(self, fs, data_batch):
        """The fused path's CapturedTrainStep, or None when
        MXNET_ENGINE_CAPTURE is off. Auto-invalidates on reshape (a new
        batch geometry changes what the closures dispatch, so the
        recording must re-warm)."""
        from .. import engine
        if not engine.capture_enabled():
            cap = fs.pop("capture", None)
            if cap is not None:  # env flipped off mid-run: drain + retire
                cap.close()
            return None
        cap = fs.get("capture")
        if cap is None:
            from ..executor import CapturedTrainStep
            cap = CapturedTrainStep(name="fit_step")
            fs["capture"] = cap
        shapes = tuple(tuple(a.shape) for a in
                       list(data_batch.data) + list(data_batch.label or []))
        prev = fs.get("capture_shapes")
        if prev is not None and prev != shapes:
            cap.invalidate("reshape: %s -> %s" % (prev, shapes))
            cap.fence()  # old-geometry steps complete before the new load
        fs["capture_shapes"] = shapes
        return cap

    def _fit_fuse_ops(self, fs, cap, data_batch, lr_arr, wd_arr):
        """(fuse_load, fuse_step) FuseOp pair lowering the captured
        fit_step into ONE fused XLA program (MXNET_ENGINE_FUSE;
        engine.FusedSequence), or (None, None) when this setup can't be
        traced faithfully. The step register carried across iterations on
        ``cap.step_var`` is ``(params, states, aux, outs)``; its writeback
        keeps ``fs``/aux_dict/outputs in sync each iteration so a bail's
        replay closures resume from exactly the published state. The
        AUTO-layout path owns compiled artifacts (learned formats) a
        plain re-trace would not reproduce, so it stays on replay; the
        ZeRO paths (MXNET_SHARDED_UPDATE stages 1-3) DO fuse — the carry
        leaves are committed-sharded before staging and FusedSequence
        folds their placement into the staged avals and fused_key, so
        the one donated program lowers with the right shardings."""
        from .. import engine
        if not engine.fuse_enabled():
            return None, None
        meta = getattr(fs["step"], "fuse", None)
        if meta is None or meta["use_auto"]:
            return None, None
        exec_ = meta["executor"]
        exec_group = self._exec_group
        dvar, svar = cap.data_var, cap.step_var
        pairs = [(n, i, False) for i, n in enumerate(exec_group.data_names)
                 if n in exec_.arg_dict]
        if exec_group.label_names and data_batch.label:
            pairs += [(n, i, True)
                      for i, n in enumerate(exec_group.label_names)
                      if n in exec_.arg_dict]
        # feed names the step reads but the batch never writes come from
        # the exec buffers, exactly like _run_impl's arg_dict fill-in
        batch_names = {n for n, _i, _l in pairs}
        extra_names = tuple(n for n in meta["data_names"]
                            if n not in batch_names and n in exec_.arg_dict)
        feed_names = tuple(n for n, _i, _l in pairs) + extra_names

        def load_feed(_db=data_batch):
            # placed on the engine worker with _load_data's exact
            # cast/sharding so fused and eager batches are bit-identical
            vals = [exec_group._place(exec_.arg_dict[n],
                                      (_db.label if is_l else _db.data)[i])
                    for n, i, is_l in pairs]
            vals += [exec_.arg_dict[n]._data for n in extra_names]
            return tuple(vals)

        def load_jax(*vals, _names=feed_names):
            return ({n: v for n, v in zip(_names, vals)},)

        fuse_load = engine.FuseOp(
            load_jax, out_vars=(dvar,), feed=load_feed,
            fingerprint="fit.load_data:v1:%r" % (feed_names,))

        step_pure = meta["step"]

        def step_feed(_lr=lr_arr, _wd=wd_arr):
            return (exec_._next_rng(), _lr, _wd)

        # the step register leads with outs so the fused program's
        # flattened output order (outs, params, states, aux) matches the
        # unfused step's return order: with the carry donated, XLA pairs
        # donated buffers to outputs in that order, and keeping the
        # orders equal keeps the fused CPU-SPMD codegen (stages 2/3
        # reduce-scatter placement) bitwise with the replay arm.
        def step_jax(data_reg, step_reg, rng, lr, wd):
            _outs, params, states, aux = step_reg
            outs, new_p, new_s, aux_up = step_pure(params, states, aux,
                                                   rng, data_reg, lr, wd)
            na = dict(aux)
            na.update(aux_up)
            return ((tuple(outs), new_p, new_s, na),)

        def step_init():
            return (tuple(o._data for o in exec_.outputs),
                    fs["params"], fs["states"],
                    {n: a._data for n, a in exec_.aux_dict.items()})

        def step_writeback(d, _svar=svar):
            outs, new_p, new_s, na = d[_svar]
            fs["params"], fs["states"] = new_p, new_s
            for n, v in na.items():
                if n in exec_.aux_dict:
                    exec_.aux_dict[n]._data = v
            exec_.outputs = [nd.NDArray(o) for o in outs]

        fuse_step = engine.FuseOp(
            step_jax, in_vars=(dvar, svar), out_vars=(svar,),
            feed=step_feed, init={svar: step_init},
            writeback=step_writeback)
        return fuse_load, fuse_step

    def _capture_fence(self):
        """Happens-before for readers of fused-step results when engine
        capture pipelines fit_step (no-op otherwise)."""
        fs = self._fused_fit
        cap = fs.get("capture") if isinstance(fs, dict) else None
        if cap is not None:
            cap.fence()

    def _close_fused_capture(self, reason=None):
        """Drain + retire the fused path's capture harness (before the
        fused state is dropped or rebuilt)."""
        fs = self._fused_fit
        cap = fs.pop("capture", None) if isinstance(fs, dict) else None
        if cap is not None:
            if reason:
                cap.invalidate(reason)
            cap.close()

    def _fused_fit_state(self):
        """Build (once) or fetch the fused-step state; None if ineligible."""
        if self._fused_fit is not None:
            return self._fused_fit or None
        import os
        eligible = (
            os.environ.get("MXNET_FUSED_FIT", "1") != "0"
            and self.optimizer_initialized
            and self._kvstore is None
            and not self._update_on_kvstore
            and self._optimizer is not None
            and self._optimizer.pure_rule() is not None
            and not self.inputs_need_grad
            and not self._monitor_installed
        )
        exec_ = self._exec_group._exec
        names = list(self._exec_group.param_names)
        if eligible and any(exec_.grad_req.get(n) != "write" for n in names):
            eligible = False
        if not eligible:
            self._fused_fit = False  # cache the negative
            return None
        rule = self._optimizer.pure_rule()
        # same state keying as the unfused path (model.py _update_params:
        # index*num_device, single device slot 0 in the sharded-exec design)
        nd_dev = len(self._context)
        idx_of = {n: i * nd_dev for i, n in enumerate(names)}

        def update_fn(params, grads, states, lr_arr, wd_arr):
            new_p, new_s = {}, {}
            for pos, n in enumerate(names):
                new_p[n], new_s[n] = rule(params[n], grads[n], states[n],
                                          lr_arr[pos], wd_arr[pos])
            return new_p, new_s

        # ZeRO-1 sharded update (Xu et al.): over the exec group's data
        # mesh, master weights + optimizer state live 1/N-sharded and the
        # step reduce-scatters grads / all-gathers updated weights inside
        # the one donated program (Executor.make_train_step mesh path)
        mesh = getattr(self._exec_group, "mesh", None)
        stage = _collectives.sharded_stage(mesh)
        z1 = stage >= 1
        step = exec_.make_train_step(update_fn, mesh=mesh)
        # device-side copies: the step donates these, and donation must not
        # delete buffers aliased by exec arg_dict / user-held NDArrays
        params, states = self._fused_snapshot(exec_, names, idx_of, mesh, z1)
        hyper_key = self._optimizer._hyperparam_key()
        self._fused_fit = {"step": step, "params": params, "states": states,
                           "names": names, "idx_of": idx_of,
                           "hyper": hyper_key, "mesh": mesh, "z1": z1,
                           "stage": stage}
        return self._fused_fit

    def _fused_snapshot(self, exec_, names, idx_of, mesh, z1):
        """Donation-safe device copies of params + optimizer state for the
        fused step. Under the ZeRO-1 path params are committed straight to
        their 1/N sharded layout and NEW optimizer state is created from the
        sharded weight (born sharded, never replicated-then-sliced);
        pre-existing state copies are resharded once here."""
        hyper_key = self._optimizer._hyperparam_key()
        if z1:
            params = _collectives.zero1_place(
                {n: exec_.arg_dict[n]._data for n in names}, mesh)
        else:
            params = {n: jnp.array(exec_.arg_dict[n]._data, copy=True)
                      for n in names}
        states = {}
        for n in names:
            i = idx_of[n]
            if z1:
                self._updater.ensure_state_sharded(i, exec_.arg_dict[n],
                                                   mesh, key=hyper_key)
                states[n] = _collectives.zero1_place(
                    state_leaves(self._updater.states[i]), mesh)
            else:
                self._updater.ensure_state(i, exec_.arg_dict[n],
                                           key=hyper_key)
                states[n] = state_leaves(self._updater.states[i], copy=True)
        return params, states

    def _refresh_fused_snapshot(self, fs):
        """Re-copy params/optimizer state from exec/updater buffers into the
        fused snapshot (after set_params / a manual update), reusing the
        already-compiled step program. Under ZeRO-1 the refreshed copies go
        straight back to the sharded layout the compiled step expects."""
        cap = fs.get("capture")
        if cap is not None:  # in-flight replayed steps finish first
            cap.fence()
        exec_ = self._exec_group._exec
        fs["params"], fs["states"] = self._fused_snapshot(
            exec_, fs["names"], fs["idx_of"], fs["mesh"], fs["z1"])
        self._fused_refresh = False
        self._fused_dirty = False

    def _materialize_fused_counts(self, fs):
        """Flush the lockstep pending-step counter into the optimizer's
        per-index update counts (fit_step's constant-lr fast path defers
        them; num_update already advanced per step)."""
        pend = fs.pop("pending_counts", 0)
        if not pend:
            return
        opt_ = self._optimizer
        counts = opt_._index_update_count
        for n in fs["names"]:
            i = fs["idx_of"][n]
            counts[i] = counts.get(i, opt_.begin_num_update) + pend

    def _sync_fused_to_exec(self):
        """Refresh executor arg buffers + updater state NDArrays from the
        fused step's threaded (donated) values."""
        self._capture_fence()  # replayed steps land in fs before we read it
        fs = self._fused_fit
        if fs:
            self._materialize_fused_counts(fs)
        if not fs or not self._fused_dirty:
            return
        exec_ = self._exec_group._exec
        for n in fs["names"]:
            p, s = fs["params"][n], fs["states"][n]
            if fs.get("z1"):
                # exec/updater storage is replicated: all-gather the 1/N
                # master shards once on the way out (checkpoint/get_params)
                p = _collectives.replicate_place(p, fs["mesh"])
                s = _collectives.replicate_place(s, fs["mesh"])
            exec_.arg_dict[n]._data = p
            write_state_leaves(self._updater.states.get(fs["idx_of"][n]), s)
        self._fused_dirty = False

    def install_monitor(self, mon):
        assert self.binded
        self._monitor_installed = True
        self._sync_fused_to_exec()
        self._close_fused_capture("monitor install")
        self._fused_fit = None  # monitor needs per-op taps: unfused path
        self._exec_group.install_monitor(mon)
