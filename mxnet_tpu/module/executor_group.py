"""Sharded executor group.

TPU-native redesign of DataParallelExecutorGroup
(python/mxnet/module/executor_group.py:79). The reference builds ONE
EXECUTOR PER DEVICE, scatters batch slices (`decide_slices`, :213-237),
fans out forward/backward, and reduces gradients through Comm/KVStore.

On TPU the idiomatic equivalent is ONE executor over a device Mesh:
- the batch axis is sharded over the mesh ("data" axis) via NamedSharding;
- parameters are replicated;
- XLA inserts the gradient all-reduce over ICI during sharding propagation
  (backward of a replicated param against sharded activations ⇒ psum),
  which is exactly CommDevice::Reduce (comm.h:211-373) without the
  hand-written P2P copies.

The public surface (forward / backward / get_outputs / update_metric /
slices bookkeeping) matches the reference so Module code is unchanged.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import ndarray as nd
from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None, compute_dtype=None):
        self.symbol = symbol
        self.compute_dtype = compute_dtype
        self.contexts = contexts
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        self.logger = logger

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [x.name if hasattr(x, "name") else x[0] for x in data_shapes]
        self.label_names = (
            [x.name if hasattr(x, "name") else x[0] for x in label_shapes]
            if label_shapes else []
        )

        self.data_shapes = data_shapes
        self.label_shapes = label_shapes

        # ---- mesh over the data axis (SPMD data parallelism) -------------
        devices = [c.jax_device() for c in contexts]
        self._single = len(devices) == 1
        if not self._single:
            self.mesh = Mesh(np.array(devices), ("data",))
            self._data_sharding = NamedSharding(self.mesh, P("data"))
            self._repl_sharding = NamedSharding(self.mesh, P())
        else:
            self.mesh = None

        # grad_req per arg
        if isinstance(grad_req, str):
            base_req = grad_req if for_training else "null"
            self.grad_req = {}
            for name in self.arg_names:
                if name in self.data_names:
                    self.grad_req[name] = base_req if inputs_need_grad else "null"
                elif name in self.label_names:
                    self.grad_req[name] = "null"
                elif name in self.fixed_param_names:
                    self.grad_req[name] = "null"
                else:
                    self.grad_req[name] = base_req
        else:
            self.grad_req = dict(grad_req)

        self._bind(shared_group)
        # reference API compat: slices over the global batch (used by
        # executor_manager-style code and tests)
        self.batch_size = (
            self.data_shapes[0].shape[0]
            if hasattr(self.data_shapes[0], "shape")
            else self.data_shapes[0][1][0]
        )
        k = len(contexts)
        step = self.batch_size // k
        self.slices = [slice(i * step, (i + 1) * step if i < k - 1 else self.batch_size)
                       for i in range(k)]

    @property
    def data_parallel_size(self):
        """Replica count along the data mesh axis (1 when single-device) —
        the N of the ZeRO-1 sharded update's 1/N state shards."""
        return 1 if self.mesh is None else int(dict(self.mesh.shape)["data"])

    # ------------------------------------------------------------------
    def _shape_of(self, desc):
        return tuple(desc.shape) if hasattr(desc, "shape") else tuple(desc[1])

    def _bind(self, shared_group):
        shapes = {}
        for d in self.data_shapes:
            shapes[d.name if hasattr(d, "name") else d[0]] = self._shape_of(d)
        for d in self.label_shapes or []:
            shapes[d.name if hasattr(d, "name") else d[0]] = self._shape_of(d)
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        ctx0 = self.contexts[0]
        args, grads, auxs = {}, {}, {}
        shared_exec = shared_group._exec if shared_group is not None else None
        for name, shape in zip(self.arg_names, arg_shapes):
            args[name] = self._alloc(shape, replicated=name not in shapes or name in self.param_names)
            if self.grad_req.get(name, "null") != "null":
                grads[name] = self._alloc(shape, replicated=name in self.param_names)
        for name, shape in zip(self.aux_names, aux_shapes):
            auxs[name] = self._alloc(shape, replicated=True)
        from ..executor import Executor

        self._exec = Executor(self.symbol, ctx0, args, grads or None, self.grad_req,
                              auxs, shared_exec=shared_exec,
                              compute_dtype=self.compute_dtype)
        self.execs = [self._exec]  # reference-compat attribute

    def _alloc(self, shape, replicated=True):
        arr = np.zeros(shape, np.float32)
        if self._single:
            return nd.array(arr, ctx=self.contexts[0])
        sharding = self._repl_sharding if replicated or shape[0] % len(self.contexts) else self._data_sharding
        return NDArray(jax.device_put(arr, sharding))

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """Scatter the batch over the mesh and run the single sharded
        executor (reference executor_group.py:364 forward)."""
        if is_train is None:
            is_train = self.for_training
        self._load_data(data_batch)
        self._exec.forward(is_train=is_train)
        return self._exec.outputs

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to run backward")
        self._exec.backward(out_grads)

    def forward_backward(self, data_batch, out_grads=None):
        """Fused train step: one jitted XLA computation for fwd+bwd."""
        self._load_data(data_batch)
        self._exec.forward_backward(out_grads)
        return self._exec.outputs

    def _put(self, target: NDArray, value):
        target._data = self._place(target, value)

    def _place(self, target: NDArray, value):
        # Keep device arrays on device: an NDArray batch feeds straight into
        # device_put (device-to-device, often a no-op) — no host round-trip.
        # The reference's H2D copy is likewise engine-async (SURVEY §3.5).
        # Split from _put so trace-and-fuse feeds place a batch EXACTLY as
        # _load_data would (same cast, same sharding) without touching the
        # exec buffers.
        tgt_dtype = target._data.dtype
        if isinstance(value, NDArray):
            arr = value._data
            if arr.dtype != tgt_dtype:
                arr = arr.astype(tgt_dtype)
        else:
            arr = np.asarray(value).astype(np.dtype(tgt_dtype), copy=False)
        if self._single:
            dev = self.contexts[0].jax_device()
            if isinstance(arr, jax.Array) and not arr.is_deleted() \
                    and arr.sharding.device_set == {dev}:
                return arr  # already resident: no transfer
            return jax.device_put(arr, dev)
        sharding = (
            self._data_sharding
            if arr.shape and arr.shape[0] % len(self.contexts) == 0
            else self._repl_sharding
        )
        return jax.device_put(arr, sharding)

    def _load_data(self, data_batch):
        for name, val in zip(self.data_names, data_batch.data):
            if name in self._exec.arg_dict:
                self._put(self._exec.arg_dict[name], val)
        if self.label_names and data_batch.label:
            for name, val in zip(self.label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    self._put(self._exec.arg_dict[name], val)

    # ------------------------------------------------------------------
    def get_outputs(self, merge_multi_context=True):
        outs = self._exec.outputs
        if merge_multi_context:
            return outs
        return [[o] for o in outs]

    def get_input_grads(self, merge_multi_context=True):
        grads = [self._exec.grad_dict.get(n) for n in self.data_names]
        if merge_multi_context:
            return grads
        return [[g] for g in grads]

    def get_params(self, arg_params, aux_params):
        for name in self.param_names:
            if name in self._exec.arg_dict:
                arg_params[name] = nd.array(self._exec.arg_dict[name].asnumpy())
        for name in self.aux_names:
            aux_params[name] = nd.array(self._exec.aux_dict[name].asnumpy())

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for name, val in (arg_params or {}).items():
            if name in self._exec.arg_dict:
                self._put(self._exec.arg_dict[name], val)
            elif not allow_extra:
                raise MXNetError("set_params: unknown argument %r" % name)
        for name, val in (aux_params or {}).items():
            if name in self._exec.aux_dict:
                self._put(self._exec.aux_dict[name], val)
            elif not allow_extra:
                raise MXNetError("set_params: unknown aux state %r" % name)

    def update_metric(self, eval_metric, labels):
        """Per-batch metric update (the one forced sync point per step, like
        the reference's asnumpy in executor_group.py:525)."""
        eval_metric.update(labels, self._exec.outputs)

    @property
    def grad_arrays(self):
        """Gradient arrays aligned 1:1 with param_arrays (reference shape
        [[per-device]]); None entry for params with grad_req null (fixed)."""
        return [[self._exec.grad_dict.get(n)] for n in self.param_names
                if n in self._exec.arg_dict]

    @property
    def param_arrays(self):
        return [[self._exec.arg_dict[n]] for n in self.param_names if n in self._exec.arg_dict]

    def install_monitor(self, mon):
        for exe in self.execs:
            mon.install(exe)
