"""Training modules (reference python/mxnet/module/, SURVEY §2.4)."""
from .base_module import BaseModule
from .module import Module
from .executor_group import DataParallelExecutorGroup
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule
