"""Generic class registry factories.

Capability parity with python/mxnet/registry.py (reference :15-141):
``get_register_func``/``get_alias_func``/``get_create_func`` attach a
string-keyed registry to a base class so subsystems (optimizers, metrics,
initializers, augmenters, ...) can be registered by name and created from
``"name"``, ``"json-config"`` or ``("name", kwargs)`` specs.
"""
from __future__ import annotations

import json

from .base import MXNetError

_REGISTRY = {}  # base_class -> {lowercased name: klass}


def get_registry(base_class):
    """Return a copy of the name->class mapping registered for base_class."""
    return dict(_REGISTRY.get(base_class, {}))


def get_register_func(base_class, nickname):
    """Make a decorator that registers subclasses of ``base_class``.

    Mirrors reference registry.py:15-52 — re-registration warns and
    overwrites, names are case-insensitive.
    """
    if base_class not in _REGISTRY:
        _REGISTRY[base_class] = {}
    registry = _REGISTRY[base_class]

    def register(klass, name=None):
        assert issubclass(klass, base_class), (
            "Can only register subclass of %s" % base_class.__name__)
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry:
            import logging
            logging.warning(
                "New %s %s.%s registered with name %s is overriding existing "
                "%s %s.%s", nickname, klass.__module__, klass.__name__, name,
                nickname, registry[name].__module__, registry[name].__name__)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname, nickname)
    return register


def get_alias_func(base_class, nickname):
    """Make a decorator that registers a class under extra alias names
    (reference registry.py:53-79)."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg

    alias.__doc__ = "Register %s under alias names" % nickname
    return alias


def get_create_func(base_class, nickname):
    """Make a ``create(spec, **kwargs)`` factory (reference registry.py:80-141).

    Accepts an existing instance, a registered name, a JSON string
    ``'{"name": {...kwargs}}'``, or name plus kwargs.
    """
    if base_class not in _REGISTRY:
        _REGISTRY[base_class] = {}
    registry = _REGISTRY[base_class]

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, (
                "%s is already an instance. Additional arguments are invalid"
                % nickname)
            return name
        if isinstance(name, dict):
            return create(**name)
        assert isinstance(name, str), (
            "%s must be of string type" % nickname)
        if name.startswith("["):
            assert not args and not kwargs
            name, kw = json.loads(name)
            return create(name, **kw)
        if name.startswith("{"):
            assert not args and not kwargs
            cfg = json.loads(name)
            return create(**cfg)
        name = name.lower()
        if name not in registry:
            raise MXNetError(
                "%s is not registered. Registered %ss: %s"
                % (name, nickname, ", ".join(sorted(registry))))
        return registry[name](*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
