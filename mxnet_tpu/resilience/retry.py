# coding: utf-8
"""Unified retry policy: jittered exponential backoff under a deadline.

One policy object replaces the hand-rolled ``while True: try/except/
sleep(0.2)`` connect loops that grew in ``kvstore_server.py`` (and that
each read ``MXNET_TPU_PS_CONNECT_TIMEOUT`` independently). The shape
follows ps-lite's resender/backoff knobs: a base delay doubling per
attempt up to a cap, multiplied by a deterministic jitter so N workers
hammering a restarting server don't reconnect in lockstep, all bounded
by a wall-clock deadline.

Env defaults (docs/env_var.md "Distributed"):

- ``MXNET_TPU_PS_CONNECT_TIMEOUT`` — deadline seconds (default 60)
- ``MXNET_TPU_PS_RETRY_BASE``      — first backoff seconds (default 0.2)
- ``MXNET_TPU_PS_RETRY_MAX``       — backoff cap seconds (default 2.0)
- ``MXNET_TPU_PS_RETRY_JITTER``    — jitter fraction in [0,1) (default 0.25)
"""
from __future__ import annotations

import os
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from ..base import MXNetError

__all__ = ["RetryPolicy", "RetryError"]


class RetryError(MXNetError):
    """Deadline exhausted; ``last_error`` holds the final attempt's failure."""

    def __init__(self, msg: str, last_error: Optional[BaseException] = None):
        super().__init__(msg)
        self.last_error = last_error


class RetryPolicy:
    """Jittered exponential backoff bounded by a deadline.

    Use either the iterator form (the attempt body stays in caller code,
    matching the old inline loops)::

        for attempt in RetryPolicy.for_connect().attempts():
            try:
                conn = Client(addr, authkey=_AUTH)
                break
            except OSError:
                continue          # attempts() sleeps, then re-yields

    or the functional form::

        conn = RetryPolicy.for_connect().call(
            lambda: Client(addr, authkey=_AUTH), retry_on=(OSError,))

    Both raise :class:`RetryError` once the deadline passes, chaining the
    last attempt's exception. ``seed`` pins the jitter sequence — the
    fault-injection tests rely on byte-identical schedules per seed.
    """

    def __init__(self, deadline_s: float = 60.0, base_s: float = 0.2,
                 max_s: float = 2.0, jitter: float = 0.25,
                 seed: Optional[int] = None):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (got %r)" % deadline_s)
        if base_s <= 0 or max_s < base_s:
            raise ValueError("need 0 < base_s <= max_s (got %r, %r)"
                             % (base_s, max_s))
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1) (got %r)" % jitter)
        self.deadline_s = float(deadline_s)
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    @classmethod
    def for_connect(cls, seed: Optional[int] = None) -> "RetryPolicy":
        """The PS connect policy, from the ``MXNET_TPU_PS_*`` env knobs.

        THE single reader of ``MXNET_TPU_PS_CONNECT_TIMEOUT`` — every
        site that used to parse it inline now builds one of these."""
        return cls(
            deadline_s=float(os.environ.get(
                "MXNET_TPU_PS_CONNECT_TIMEOUT", "60")),
            base_s=float(os.environ.get("MXNET_TPU_PS_RETRY_BASE", "0.2")),
            max_s=float(os.environ.get("MXNET_TPU_PS_RETRY_MAX", "2.0")),
            jitter=float(os.environ.get("MXNET_TPU_PS_RETRY_JITTER", "0.25")),
            seed=seed)

    def backoffs(self) -> Iterator[float]:
        """The raw sleep schedule: base*2^k clamped to max, each scaled by
        ``1 - jitter*u`` (u uniform in [0,1)) so jittered sleeps only ever
        SHORTEN the wait — the deadline stays an upper bound."""
        delay = self.base_s
        while True:
            j = 1.0 - self.jitter * self._rng.random()
            yield delay * j
            delay = min(delay * 2.0, self.max_s)

    def attempts(self) -> Iterator[int]:
        """Yield attempt indices 0, 1, 2, ... sleeping the backoff between
        them, until the deadline passes; the final yield happens exactly at
        deadline expiry so the last attempt can still succeed. The caller
        ``break``s on success; exhausting the iterator means every attempt
        inside the window failed (raise or fall through as appropriate)."""
        deadline = time.monotonic() + self.deadline_s
        sched = self.backoffs()
        k = 0
        while True:
            yield k
            k += 1
            now = time.monotonic()
            if now >= deadline:
                return
            time.sleep(min(next(sched), max(0.0, deadline - now)))

    def call(self, fn: Callable[[], object],
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             what: str = "operation"):
        """Run ``fn`` under the policy; return its result. Exceptions not
        in ``retry_on`` propagate immediately (they are bugs, not flakes)."""
        last: Optional[BaseException] = None
        for _ in self.attempts():
            try:
                return fn()
            except retry_on as e:
                last = e
        raise RetryError(
            "%s failed for %.1fs (last error: %s: %s)"
            % (what, self.deadline_s, type(last).__name__ if last else "?",
               last), last_error=last)
