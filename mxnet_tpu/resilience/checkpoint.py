# coding: utf-8
"""Async sharded checkpoints with crash-safe commit and resharding.

Layout on disk for ``save_sharded(prefix, step, ..., num_shards=N)``::

    {prefix}-step0000042.shard-000-of-004.ckpt      one per rank
    ...
    {prefix}-step0000042.manifest.json              the COMMIT record

Every tensor is flattened to 1-D and split into N near-equal CONTIGUOUS
ranges (``per, rem = divmod(size, N)``; rank r takes ``per + (r < rem)``
elements — the same plan ``PSClient`` uses for big-array striping). That
makes resharding pure concatenation/re-slicing: a checkpoint written at
dp=N restores BITWISE at any dp=M, params and optimizer state alike
(the ZeRO-1 story from Xu et al.: each replica owns — and therefore
checkpoints — 1/N of the f32 masters + optimizer state).

Shard files are a deterministic binary format (NOT ``np.savez``, whose
zip container embeds timestamps — byte-identical round-trips are part
of the contract here)::

    magic  b"MXTPUCKPT\\x01"
    u64le  header length
    json   {"entries": [[name, dtype, count], ...]}   (sorted by name)
    raw    concatenated little-endian buffers, entry order

Commit protocol (the crash-safety argument):

1. every shard serializes, writes ``*.tmp``, then ``os.replace``s into
   place — a torn write can never be mistaken for a shard;
2. the manifest write is an engine op ordered AFTER all N shard ops
   (``engine.push_file_write(after_paths=shard_paths)``) and itself goes
   tmp → ``os.replace``;
3. therefore at any crash point the newest *manifest* on disk describes
   only fully-written shards, and :func:`latest_step` (which requires a
   parseable manifest + all shards present with the recorded sizes)
   never selects a torn checkpoint. CRCs are verified at load.

All writes ride the engine's file-write vars (one per path), so
``async_write=True`` never blocks the train loop; the returned
:class:`CheckpointHandle` exposes ``done()``/``wait()`` and surfaces
write errors exactly like other async file ops.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import engine
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["CheckpointHandle", "RestoredCheckpoint", "save_sharded",
           "load_sharded", "reshard", "latest_step", "list_steps",
           "fingerprint_arrays", "MANIFEST_VERSION"]

MANIFEST_VERSION = 1
_MAGIC = b"MXTPUCKPT\x01"

_ckpt_total = _telemetry.registry.counter(
    "resilience_checkpoints_total", help="Sharded checkpoints committed")
_ckpt_bytes = _telemetry.registry.counter(
    "resilience_checkpoint_bytes_total",
    help="Bytes written by sharded checkpoints (shards + manifests)")
_ckpt_last_ms = _telemetry.registry.gauge(
    "resilience_checkpoint_last_ms",
    help="Wall ms of the last checkpoint commit (snapshot to manifest)")
_restore_total = _telemetry.registry.counter(
    "resilience_restores_total", help="Sharded checkpoints restored")


def _shard_path(prefix: str, step: int, rank: int, num: int) -> str:
    return "%s-step%07d.shard-%03d-of-%03d.ckpt" % (prefix, step, rank, num)


def _manifest_path(prefix: str, step: int) -> str:
    return "%s-step%07d.manifest.json" % (prefix, step)


def _shard_range(size: int, rank: int, num: int) -> Tuple[int, int]:
    """Contiguous [lo, hi) of a flattened size-``size`` tensor owned by
    ``rank`` of ``num`` (PSClient._plan split: remainder to low ranks)."""
    per, rem = divmod(size, num)
    lo = rank * per + min(rank, rem)
    return lo, lo + per + (1 if rank < rem else 0)


def fingerprint_arrays(arrays: Dict[str, np.ndarray]) -> str:
    """Model fingerprint: sha1 over the sorted (name, shape, dtype)
    catalog. Restoring into a module with a different catalog is a bug
    the manifest check turns into a clear error."""
    h = hashlib.sha1()
    for name in sorted(arrays):
        a = arrays[name]
        h.update(("%s|%s|%s;" % (name, tuple(a.shape),
                                 np.dtype(a.dtype).str)).encode())
    return h.hexdigest()


def _serialize_shard(entries: List[Tuple[str, np.ndarray]]) -> bytes:
    """Deterministic shard bytes for [(name, 1-D slice), ...]."""
    header = json.dumps(
        {"entries": [[n, np.dtype(a.dtype).str, int(a.size)]
                     for n, a in entries]},
        sort_keys=True, separators=(",", ":")).encode()
    buf = io.BytesIO()
    buf.write(_MAGIC)
    buf.write(len(header).to_bytes(8, "little"))
    buf.write(header)
    for _, a in entries:
        buf.write(np.ascontiguousarray(a).tobytes())
    return buf.getvalue()


def _parse_shard(data: bytes, path: str) -> Dict[str, np.ndarray]:
    if data[:len(_MAGIC)] != _MAGIC:
        raise MXNetError("bad shard magic in %s" % path)
    off = len(_MAGIC)
    hlen = int.from_bytes(data[off:off + 8], "little")
    off += 8
    header = json.loads(data[off:off + hlen])
    off += hlen
    out: Dict[str, np.ndarray] = {}
    for name, dtype, count in header["entries"]:
        dt = np.dtype(dtype)
        nbytes = count * dt.itemsize
        out[name] = np.frombuffer(
            data[off:off + nbytes], dtype=dt).copy()
        off += nbytes
    if off != len(data):
        raise MXNetError("trailing bytes in shard %s" % path)
    return out


def _atomic_write(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class CheckpointHandle:
    """Async-commit handle: ``done()`` probes, ``wait()`` blocks and
    surfaces any write failure (fault-injected or real)."""

    def __init__(self, prefix: str, step: int, paths: List[str]):
        self.prefix = prefix
        self.step = step
        self.paths = list(paths)
        self._fence = engine.fence(
            [engine.file_var(p) for p in self.paths], name="ckpt_fence")

    def done(self) -> bool:
        return self._fence.done()

    def wait(self, timeout: Optional[float] = None) -> "CheckpointHandle":
        """Block until every shard + the manifest op completed; re-raise
        the first recorded write error (the checkpoint is then NOT
        committed — the previous manifest stays authoritative)."""
        self._fence.wait(timeout)
        first = None
        for p in self.paths:
            try:
                engine.wait_for_file(p)
            except BaseException as e:
                if first is None:
                    first = e
        if first is not None:
            raise first
        return self


def save_sharded(prefix: str, step: int, arrays: Dict[str, np.ndarray],
                 num_shards: int, *, opt_meta: Optional[dict] = None,
                 fingerprint: Optional[str] = None,
                 async_write: bool = True) -> CheckpointHandle:
    """Write ``arrays`` as ``num_shards`` shard files + a manifest.

    ``arrays`` maps flat names (the module layer uses ``param:<name>``,
    ``aux:<name>``, ``opt:<name>:<leaf>``) to host ndarrays. The arrays
    themselves ARE the snapshot — ``module.get_checkpoint_state()``
    returns fresh host copies, so the device->host copy the caller
    already paid is the only synchronous cost; slicing, serialization,
    CRC, and disk I/O all run inside the background engine ops. The
    contract: callers must not mutate ``arrays`` until the handle
    commits (the train loop updating *device* weights is fine). Each
    shard is its own engine op (one per replica in a real dp run), the
    manifest ordered after all of them. ``opt_meta`` carries scalar
    optimizer bookkeeping (update counts) that belongs to no shard."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1 (got %d)" % num_shards)
    t0 = time.monotonic()
    names = sorted(arrays)
    catalog = {n: {"shape": list(arrays[n].shape),
                   "dtype": np.dtype(arrays[n].dtype).str} for n in names}
    fp = fingerprint or fingerprint_arrays(arrays)
    shard_paths = [_shard_path(prefix, step, r, num_shards)
                   for r in range(num_shards)]
    # shard idx -> (crc32, nbytes), written by the shard ops; the
    # manifest op is ordered strictly after every shard op (the
    # after_paths commit edge), so reading it there is race-free
    results: Dict[int, tuple] = {}

    with _telemetry.span("resilience.checkpoint", domain="resilience",
                         step=step, num_shards=num_shards):
        # faults/maybe_raise stays inside the pushed op so an injected
        # failure exercises the real async-error path.
        def _shard_writer(r, path):
            def run():
                from . import faults
                faults.maybe_raise("ckpt_shard:%s" % os.path.basename(path))
                entries = []
                for n in names:
                    flat = np.ascontiguousarray(arrays[n]).reshape(-1)
                    lo, hi = _shard_range(flat.size, r, num_shards)
                    entries.append((n, flat[lo:hi]))
                blob = _serialize_shard(entries)
                results[r] = (zlib.crc32(blob) & 0xFFFFFFFF, len(blob))
                _atomic_write(path, blob)
                _ckpt_bytes.inc(len(blob))
            return run

        for r, path in enumerate(shard_paths):
            engine.push_file_write(path, _shard_writer(r, path),
                                   wait=False, name="ckpt_shard")

        mpath = _manifest_path(prefix, step)

        def _manifest_writer():
            from . import faults
            faults.maybe_raise("ckpt_manifest")
            if len(results) != num_shards:
                raise MXNetError(
                    "%d of %d shard writes failed; step %d not committed"
                    % (num_shards - len(results), num_shards, step))
            manifest = {
                "version": MANIFEST_VERSION,
                "step": int(step),
                "dp": int(num_shards),
                "fingerprint": fp,
                "catalog": catalog,
                "shards": [{"file": os.path.basename(p),
                            "crc32": results[r][0], "bytes": results[r][1]}
                           for r, p in enumerate(shard_paths)],
                "opt_meta": opt_meta or {},
            }
            mblob = json.dumps(manifest, sort_keys=True, indent=1).encode()
            _atomic_write(mpath, mblob)
            _ckpt_bytes.inc(len(mblob))
            _ckpt_total.inc()
            _ckpt_last_ms.set((time.monotonic() - t0) * 1000.0)

        # the commit edge: manifest op cannot run before any shard op
        engine.push_file_write(mpath, _manifest_writer, wait=False,
                               name="ckpt_manifest",
                               after_paths=shard_paths)

    handle = CheckpointHandle(prefix, step, shard_paths + [mpath])
    if not async_write:
        handle.wait()
    return handle


def list_steps(prefix: str) -> List[int]:
    """Steps with a parseable, fully-present manifest, ascending."""
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    if not os.path.isdir(d):
        return []
    out = []
    for fn in os.listdir(d):
        if not (fn.startswith(base + "-step")
                and fn.endswith(".manifest.json")):
            continue
        try:
            step = int(fn[len(base) + 5:-len(".manifest.json")])
        except ValueError:
            continue
        if _manifest_ok(prefix, step):
            out.append(step)
    return sorted(out)


def _manifest_ok(prefix: str, step: int) -> bool:
    try:
        with open(_manifest_path(prefix, step)) as f:
            m = json.load(f)
        d = os.path.dirname(prefix) or "."
        for sh in m["shards"]:
            p = os.path.join(d, sh["file"])
            if os.path.getsize(p) != sh["bytes"]:
                return False
        return m.get("version") == MANIFEST_VERSION
    except (OSError, ValueError, KeyError):
        return False


def latest_step(prefix: str) -> Optional[int]:
    """Newest committed step, or None. Only manifests whose shards are
    all on disk at the recorded sizes count — a crash mid-commit leaves
    the previous checkpoint authoritative."""
    steps = list_steps(prefix)
    return steps[-1] if steps else None


class RestoredCheckpoint:
    """What :func:`load_sharded` returns.

    - ``arrays``: full (reassembled) name → ndarray dict
    - ``shards``: per-rank dicts of 1-D slices at ``dp`` (= ``new_dp``
      when given — the re-split view a resuming rank consumes)
    - ``manifest`` / ``step`` / ``opt_meta`` / ``fingerprint``
    """

    def __init__(self, arrays, shards, manifest):
        self.arrays: Dict[str, np.ndarray] = arrays
        self.shards: List[Dict[str, np.ndarray]] = shards
        self.manifest: dict = manifest
        self.step: int = manifest["step"]
        self.dp: int = len(shards)
        self.opt_meta: dict = manifest.get("opt_meta", {})
        self.fingerprint: str = manifest["fingerprint"]


def load_sharded(prefix: str, step: Optional[int] = None,
                 new_dp: Optional[int] = None,
                 expect_fingerprint: Optional[str] = None
                 ) -> RestoredCheckpoint:
    """Load a committed checkpoint; reassemble (and optionally re-split).

    ``step=None`` picks :func:`latest_step`. ``new_dp`` re-splits for a
    different data-parallel width — a job checkpointed at dp=N resumes
    at dp=M with every element bit-identical (contiguous ranges only
    move between shards, they never change). CRCs and the catalog are
    validated; ``expect_fingerprint`` guards against restoring into the
    wrong model."""
    if step is None:
        step = latest_step(prefix)
        if step is None:
            raise MXNetError("no committed checkpoint under prefix %r"
                             % prefix)
    mpath = _manifest_path(prefix, step)
    with _telemetry.span("resilience.restore", domain="resilience",
                         step=step):
        with open(mpath) as f:
            manifest = json.load(f)
        if manifest.get("version") != MANIFEST_VERSION:
            raise MXNetError("manifest %s: unsupported version %r"
                             % (mpath, manifest.get("version")))
        if (expect_fingerprint is not None
                and manifest["fingerprint"] != expect_fingerprint):
            raise MXNetError(
                "checkpoint fingerprint mismatch for %s: manifest %s != "
                "expected %s (different model catalog)"
                % (mpath, manifest["fingerprint"], expect_fingerprint))
        d = os.path.dirname(prefix) or "."
        pieces: List[Dict[str, np.ndarray]] = []
        for sh in manifest["shards"]:
            spath = os.path.join(d, sh["file"])
            engine.wait_for_file(spath)  # never half-read an async write
            with open(spath, "rb") as f:
                data = f.read()
            crc = zlib.crc32(data) & 0xFFFFFFFF
            if crc != sh["crc32"]:
                raise MXNetError(
                    "shard %s corrupt: crc32 %08x != manifest %08x"
                    % (spath, crc, sh["crc32"]))
            pieces.append(_parse_shard(data, spath))

        arrays: Dict[str, np.ndarray] = {}
        for name, spec in manifest["catalog"].items():
            flat = np.concatenate([p[name] for p in pieces])
            shape = tuple(spec["shape"])
            if flat.size != int(np.prod(shape) if shape else 1):
                raise MXNetError(
                    "shard reassembly of %r: got %d elements, catalog "
                    "says %s" % (name, flat.size, shape))
            arrays[name] = flat.reshape(shape).astype(spec["dtype"],
                                                      copy=False)

        dp = int(new_dp) if new_dp else int(manifest["dp"])
        shards = []
        for r in range(dp):
            sd = {}
            for name in sorted(arrays):
                flat = arrays[name].reshape(-1)
                lo, hi = _shard_range(flat.size, r, dp)
                sd[name] = flat[lo:hi]
            shards.append(sd)
        _restore_total.inc()
    return RestoredCheckpoint(arrays, shards, manifest)


def reshard(prefix: str, step: int, new_dp: int,
            out_prefix: Optional[str] = None,
            async_write: bool = False) -> CheckpointHandle:
    """Rewrite the checkpoint at ``new_dp`` shards (same step). The
    dp=4 → dp=2 → dp=4 round-trip is bitwise on every tensor."""
    rc = load_sharded(prefix, step)
    return save_sharded(out_prefix or prefix, step, rc.arrays, new_dp,
                        opt_meta=rc.opt_meta, fingerprint=rc.fingerprint,
                        async_write=async_write)
