# coding: utf-8
"""Deterministic, seed-driven fault injection (``MXNET_FAULT_PLAN``).

Nothing in a healthy tree ever *exercises* a failure; this module makes
failure a first-class, replayable input. A *fault plan* is a small
``;``-separated DSL naming faults to inject at instrumented sites:

    MXNET_FAULT_PLAN="seed=7; engine_error op=ckpt_shard nth=2; \
kill_rank rank=1 step=5; conn_drop op=push nth=3; delay op=pull nth=2 ms=40"

Entry grammar: ``kind k=v k=v ...``. Kinds and the sites that honour them:

``engine_error op=<substr> [nth=K] [p=F]``
    The matching engine-op / file-write raises :class:`InjectedFault`
    (checkpoint writes consult :func:`maybe_raise` inside the op body, so
    the error takes the REAL async-error path: ``engine._file_errs`` →
    next sync point).
``conn_drop op=<substr> [nth=K] [p=F]``
    ``PSClient`` closes the socket mid-RPC and raises ``OSError`` — the
    exact failure a killed server produces.
``delay op=<substr> [nth=K] [p=F] ms=<float>``
    The matching site sleeps ``ms`` before proceeding (reply-delay /
    slow-network simulation).
``kill_rank rank=R step=S``
    From training step ``S`` on, rank ``R`` reads as dead
    (:func:`killed_ranks`, merged into ``parallel.dist.dead_nodes``);
    :func:`revive` models the rank's restart and consumes the entry.

Matching is DETERMINISTIC: each entry keeps its own occurrence counter
per matching site call; ``nth=K`` fires on the K-th match (1-based),
once. ``p=F`` fires with probability F from the plan's seeded RNG —
same seed, same plan, same call sequence ⇒ byte-identical fault
schedule. Counters live under one leaf lock (``resilience.faults._lock``,
rank 100 in the analysis LOCK_HIERARCHY): sites may be called from
engine workers and the training thread concurrently.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Set

from ..base import MXNetError
from .. import telemetry as _telemetry

__all__ = ["InjectedFault", "install", "clear", "active", "plan_repr",
           "maybe_raise", "maybe_drop", "maybe_delay",
           "killed_ranks", "revive", "faults_injected"]


class InjectedFault(MXNetError):
    """An error raised on purpose by the fault plan (never by real code)."""


_KINDS = ("engine_error", "conn_drop", "delay", "kill_rank")

_counter = _telemetry.registry.counter(
    "resilience_faults_injected_total",
    help="Faults fired by the MXNET_FAULT_PLAN harness")


class _Fault:
    __slots__ = ("kind", "op", "nth", "p", "ms", "rank", "step",
                 "seen", "fired")

    def __init__(self, kind: str, kv: Dict[str, str], idx: int):
        self.kind = kind
        self.op = kv.pop("op", None)
        self.nth = int(kv.pop("nth", "1"))
        self.p = float(kv["p"]) if "p" in kv else None
        kv.pop("p", None)
        self.ms = float(kv.pop("ms", "0"))
        self.rank = int(kv.pop("rank", "-1"))
        self.step = int(kv.pop("step", "0"))
        if kv:
            raise ValueError("fault entry %d (%s): unknown key(s) %s"
                             % (idx, kind, sorted(kv)))
        if kind == "kill_rank" and self.rank < 0:
            raise ValueError("kill_rank needs rank=R")
        if kind == "delay" and self.ms <= 0:
            raise ValueError("delay needs ms=<positive float>")
        self.seen = 0    # matching site calls so far (under _lock)
        self.fired = False

    def describe(self) -> str:
        bits = [self.kind]
        if self.op is not None:
            bits.append("op=%s" % self.op)
        if self.kind == "kill_rank":
            bits.append("rank=%d step=%d" % (self.rank, self.step))
        elif self.p is not None:
            bits.append("p=%g" % self.p)
        else:
            bits.append("nth=%d" % self.nth)
        if self.kind == "delay":
            bits.append("ms=%g" % self.ms)
        return " ".join(bits)


_lock = threading.Lock()          # leaf: rank 100, nothing acquired inside
_plan: List[_Fault] = []
_rng = random.Random(0)
_env_loaded = False
_revived: Set[int] = set()
_injected = 0   # own tally: authoritative even when telemetry is disabled


def _parse(text: str) -> tuple:
    faults: List[_Fault] = []
    seed = 0
    for idx, raw in enumerate(text.split(";")):
        entry = raw.strip()
        if not entry:
            continue
        toks = entry.split()
        if toks[0].startswith("seed="):
            seed = int(toks[0][5:])
            toks = toks[1:]
            if not toks:
                continue
        kind = toks[0]
        if kind not in _KINDS:
            raise ValueError(
                "fault entry %d: unknown kind %r (expected one of %s)"
                % (idx, kind, "/".join(_KINDS)))
        kv = {}
        for t in toks[1:]:
            if "=" not in t:
                raise ValueError("fault entry %d: bad token %r (want k=v)"
                                 % (idx, t))
            k, v = t.split("=", 1)
            kv[k] = v
        faults.append(_Fault(kind, kv, idx))
    return faults, seed


def install(plan: Optional[str]):
    """Install ``plan`` (the ``MXNET_FAULT_PLAN`` DSL) process-wide;
    ``None``/empty clears. Resets all occurrence counters and the RNG."""
    global _plan, _rng, _env_loaded, _revived, _injected
    faults, seed = _parse(plan) if plan else ([], 0)
    with _lock:
        _plan = faults
        _rng = random.Random(seed)
        _revived = set()
        _injected = 0
        _env_loaded = True   # an explicit install overrides the env


def clear():
    """Remove the active plan (env plan will NOT be re-read)."""
    install(None)


def _ensure_loaded():
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
    env = os.environ.get("MXNET_FAULT_PLAN")
    if env:
        install(env)


def active() -> bool:
    """True when a non-empty plan is installed (or set via the env)."""
    _ensure_loaded()
    with _lock:
        return bool(_plan)


def plan_repr() -> List[str]:
    """Human-readable entries of the active plan (for logs/tests)."""
    _ensure_loaded()
    with _lock:
        return [f.describe() for f in _plan]


def faults_injected() -> int:
    """Total faults fired since the last :func:`install`."""
    with _lock:
        return _injected


def _fired(n: int = 1):
    global _injected
    with _lock:
        _injected += n
    _counter.inc(n)   # counter has its own lock: inc OUTSIDE _lock (leaf)


def _match(kind: str, op: Optional[str]) -> Optional[_Fault]:
    """Find-and-arm under _lock; returns the fault iff it fires NOW."""
    _ensure_loaded()
    if not _plan:  # fast path: no plan, no lock (GIL-safe read)
        return None
    with _lock:
        for f in _plan:
            if f.kind != kind or f.fired:
                continue
            if f.op is not None and (op is None or f.op not in op):
                continue
            f.seen += 1
            if f.p is not None:
                if _rng.random() >= f.p:
                    continue
            elif f.seen != f.nth:
                continue
            f.fired = True
            return f
    return None


def maybe_raise(op: str):
    """Site hook for ``engine_error``: raise :class:`InjectedFault` when
    the plan says so. Call INSIDE the op body so the error takes the same
    propagation path a real failure would."""
    f = _match("engine_error", op)
    if f is not None:
        _fired()
        raise InjectedFault("injected engine_error at op %r (%s)"
                            % (op, f.describe()))


def maybe_drop(op: str) -> bool:
    """Site hook for ``conn_drop``: True when the caller should sever its
    connection and raise the resulting OSError itself."""
    f = _match("conn_drop", op)
    if f is not None:
        _fired()
        return True
    return False


def maybe_delay(op: str):
    """Site hook for ``delay``: sleep the planned ms when matched."""
    f = _match("delay", op)
    if f is not None:
        _fired()
        time.sleep(f.ms / 1000.0)


def killed_ranks(step: Optional[int] = None) -> Set[int]:
    """Ranks the plan declares dead at training step ``step`` (all armed
    kills when ``step`` is None), minus ranks revived since. Feeds
    ``parallel.dist.dead_nodes`` so the supervisor's normal dead-node
    poll sees simulated deaths through the same surface as real ones."""
    _ensure_loaded()
    out: Set[int] = set()
    newly_fired = 0
    with _lock:
        for f in _plan:
            if f.kind != "kill_rank" or f.rank in _revived:
                continue
            if step is None or step >= f.step:
                if not f.fired:
                    f.fired = True
                    newly_fired += 1
                out.add(f.rank)
    if newly_fired:
        _fired(newly_fired)
    return out


def revive(rank: int):
    """Model the dead rank's restart: it stops reading as dead. The
    supervisor calls this once recovery has restored state — a second
    ``kill_rank`` entry for the same rank would fire afresh only via a
    new :func:`install`."""
    with _lock:
        _revived.add(rank)
