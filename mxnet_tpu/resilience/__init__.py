# coding: utf-8
"""mxnet_tpu.resilience — elastic fault-tolerant training.

Four pieces (docs/fault_tolerance.md):

- :mod:`~mxnet_tpu.resilience.checkpoint` — async sharded checkpoints
  with a crash-safe manifest commit and restore-with-resharding
  (``save_sharded`` / ``load_sharded`` / ``reshard`` / ``latest_step``);
- :mod:`~mxnet_tpu.resilience.supervisor` — ``TrainingSupervisor``,
  the poll/restore/resume train loop;
- :mod:`~mxnet_tpu.resilience.retry` — ``RetryPolicy``, the one
  jittered-backoff-under-deadline implementation (PS connects use it);
- :mod:`~mxnet_tpu.resilience.faults` — the deterministic
  ``MXNET_FAULT_PLAN`` fault-injection harness that makes failure a
  replayable test input.
"""
from . import checkpoint, faults, retry, supervisor
from .checkpoint import (CheckpointHandle, RestoredCheckpoint,
                         fingerprint_arrays, latest_step, list_steps,
                         load_sharded, reshard, save_sharded)
from .faults import InjectedFault
from .retry import RetryError, RetryPolicy
from .supervisor import RecoveryError, TrainingSupervisor

__all__ = [
    "checkpoint", "faults", "retry", "supervisor",
    "CheckpointHandle", "RestoredCheckpoint", "fingerprint_arrays",
    "latest_step", "list_steps", "load_sharded", "reshard",
    "save_sharded", "InjectedFault", "RetryError", "RetryPolicy",
    "RecoveryError", "TrainingSupervisor",
]
