# coding: utf-8
"""Training supervisor: poll for dead ranks, restore, resume.

``TrainingSupervisor.run(batch_fn, num_steps)`` owns the train loop a
preemption-survivable job needs:

- every ``checkpoint_interval`` completed steps it snapshots the
  module's full training state (f32 masters + aux + optimizer state +
  update counts, ``Module.get_checkpoint_state``) and commits it as an
  async sharded checkpoint (``checkpoint.save_sharded``) — the loop
  never blocks on disk;
- between steps it polls the failure surfaces: ``kvstore.num_dead_node``
  (PS heartbeats), ``parallel.dist.dead_nodes`` (which folds in
  ``MXNET_FAULT_PLAN`` simulated kills), and engine-op errors observed
  via ``engine.set_error_handler``;
- on a detected death it pauses, drains in-flight checkpoint writes
  (a fault-injected write failure just means that checkpoint never
  committed — the previous manifest stays authoritative), restores the
  newest committed checkpoint into the module, revives the simulated
  rank, and resumes from the restored step.

Because ``batch_fn(step)`` is deterministic (replayable by step index —
the contract MXNet's epoch-seeded DataIter reset gives for free), a
recovered run replays the lost steps exactly and its per-step weights
are step-level equivalent to an uninterrupted run; the kill-a-rank
dryrun (CI stage "fault") asserts precisely that.

Single-threaded by design: polling happens BETWEEN steps on the
training thread, so the supervisor needs no lock of its own (the
engine error hook only appends to a list under the GIL).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Set

from .. import engine
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..parallel import dist
from . import checkpoint as _ckpt
from . import faults
from .retry import RetryPolicy

__all__ = ["TrainingSupervisor", "RecoveryError"]

_log = logging.getLogger("mxnet_tpu")

_recoveries = _telemetry.registry.counter(
    "resilience_recoveries_total",
    help="Successful dead-rank recoveries (restore + resume)")


class RecoveryError(MXNetError):
    """Recovery impossible (no committed checkpoint / budget exhausted)."""


class TrainingSupervisor:
    """Elastic train-loop wrapper for a bound+initialized ``Module``.

    Parameters
    ----------
    module : Module — bound, params + optimizer initialized.
    prefix : checkpoint path prefix (directory must exist).
    checkpoint_interval : commit every N completed steps (default 10).
    num_shards : shard fan-out; default = the module's device count.
    kvstore : optional KVStore whose ``num_dead_node`` joins the poll.
    poll_every : poll the failure surfaces every N steps (default 1).
    async_write : overlap checkpoint IO with training (default True).
    max_recoveries : give up (RecoveryError) after this many restores.
    retry : RetryPolicy for the restore itself (transient-IO armor).
    """

    def __init__(self, module, prefix: str, *,
                 checkpoint_interval: int = 10,
                 num_shards: Optional[int] = None,
                 kvstore=None, poll_every: int = 1,
                 async_write: bool = True, max_recoveries: int = 3,
                 retry: Optional[RetryPolicy] = None):
        if checkpoint_interval < 1:
            raise ValueError("checkpoint_interval must be >= 1")
        self._module = module
        self._prefix = prefix
        self._interval = int(checkpoint_interval)
        self._num_shards = int(num_shards
                               or len(getattr(module, "_context", [0])))
        self._kvstore = kvstore
        self._poll_every = max(1, int(poll_every))
        self._async = bool(async_write)
        self._max_recoveries = int(max_recoveries)
        self._retry = retry or RetryPolicy(deadline_s=10.0, base_s=0.05,
                                           max_s=0.5, seed=0)
        self._fingerprint: Optional[str] = None
        self._handles: List[_ckpt.CheckpointHandle] = []
        self._op_errors: List[tuple] = []
        self.recoveries = 0
        self.checkpoints = 0

    # --- failure surfaces -------------------------------------------------
    def _dead(self, step: int) -> Set[int]:
        dead = set(dist.dead_nodes(step))
        kv = self._kvstore
        if kv is not None:
            try:
                if kv.num_dead_node(timeout_sec=0) > 0:
                    dead.add(-1)  # PS-reported death (rank unknown here)
            except TypeError:
                if kv.num_dead_node(0) > 0:
                    dead.add(-1)
        return dead

    def _on_op_error(self, name: str, exc: BaseException):
        # runs ON an engine worker: just record (list.append is atomic);
        # the training thread acts at its next poll. Checkpoint-write
        # failures are NOT failures of the run — they surface (and are
        # tolerated) through the file-error path on drain instead.
        if not name.startswith("ckpt_"):
            self._op_errors.append((name, exc))

    # --- checkpointing ----------------------------------------------------
    def checkpoint_now(self, step: int) -> _ckpt.CheckpointHandle:
        """Snapshot + commit (async unless configured otherwise)."""
        arrays, opt_meta = self._module.get_checkpoint_state()
        if self._fingerprint is None:
            self._fingerprint = _ckpt.fingerprint_arrays(arrays)
        h = _ckpt.save_sharded(self._prefix, step, arrays,
                               self._num_shards, opt_meta=opt_meta,
                               fingerprint=self._fingerprint,
                               async_write=self._async)
        self._handles.append(h)
        self.checkpoints += 1
        return h

    def _drain_writes(self):
        """Wait out in-flight checkpoint writes; a failed write only
        means that checkpoint never committed."""
        for h in self._handles:
            try:
                h.wait()
            except BaseException as e:
                _log.warning("supervisor: checkpoint step %d failed "
                             "(not committed): %s", h.step, e)
        self._handles = []

    # --- recovery ---------------------------------------------------------
    def _recover(self, dead: Set[int], at_step: int) -> int:
        self.recoveries += 1
        if self.recoveries > self._max_recoveries:
            raise RecoveryError(
                "recovery budget exhausted (%d) — dead ranks %s at step %d"
                % (self._max_recoveries, sorted(dead), at_step))
        with _telemetry.span("resilience.recover", domain="resilience",
                             step=at_step, dead=len(dead)):
            _log.warning("supervisor: dead rank(s) %s detected at step %d"
                         " — pausing for restore", sorted(dead), at_step)
            self._drain_writes()
            self._op_errors = []
            committed = _ckpt.latest_step(self._prefix)
            if committed is None:
                raise RecoveryError(
                    "no committed checkpoint under %r to restore from"
                    % self._prefix)
            rc = self._retry.call(
                lambda: _ckpt.load_sharded(
                    self._prefix, committed, new_dp=self._num_shards,
                    expect_fingerprint=self._fingerprint),
                retry_on=(OSError,), what="checkpoint restore")
            self._module.restore_checkpoint_state(rc.arrays, rc.opt_meta)
            for r in dead:
                if r >= 0:
                    faults.revive(r)
            _recoveries.inc()
            _log.warning("supervisor: restored step %d, resuming",
                         committed)
        return committed

    # --- the loop ---------------------------------------------------------
    def run(self, batch_fn: Callable[[int], object], num_steps: int,
            begin_step: int = 0) -> int:
        """Run steps ``begin_step..num_steps-1`` with supervision;
        returns the number of completed steps. ``batch_fn(step)`` must
        be deterministic in ``step`` — recovery replays lost steps.

        If a committed checkpoint newer than ``begin_step`` already
        exists under the prefix (a restarted process), training resumes
        from it instead of ``begin_step``."""
        completed = begin_step
        existing = _ckpt.latest_step(self._prefix)
        if existing is not None and existing > completed:
            rc = _ckpt.load_sharded(self._prefix, existing,
                                    new_dp=self._num_shards)
            self._fingerprint = rc.fingerprint
            self._module.restore_checkpoint_state(rc.arrays, rc.opt_meta)
            completed = existing
            _log.info("supervisor: resuming from committed step %d",
                      completed)
        prev_handler = engine.set_error_handler(self._on_op_error)
        try:
            if existing is None:
                # a restore point must exist before the first failure
                self.checkpoint_now(completed).wait()
            while completed < num_steps:
                if (completed % self._poll_every) == 0 or self._op_errors:
                    dead = self._dead(completed)
                    if self._op_errors:
                        _log.warning("supervisor: engine op error(s) %s",
                                     [n for n, _ in self._op_errors])
                        dead.add(-1)
                    if dead:
                        completed = self._recover(dead, completed)
                        continue
                self._module.fit_step(batch_fn(completed))
                completed += 1
                if (completed % self._interval == 0
                        or completed == num_steps):
                    self.checkpoint_now(completed)
            self._drain_writes()
        finally:
            engine.set_error_handler(prev_handler)
        return completed
