"""Symbolic graph API.

TPU-native analogue of nnvm::Symbol + python/mxnet/symbol.py. A Symbol is a
list of output entries over a DAG of nodes; composing symbols builds the
graph; ``bind``/``simple_bind`` compile it — here to ONE jitted XLA
computation for forward and one for backward (the north-star "single HLO per
symbolic subgraph"), instead of the reference's per-node engine ops
(graph_executor.cc:567-679). Shape inference: forward shapes via
jax.eval_shape; parameter shapes via per-op rules (ops/shape_rules.py),
replacing nnvm InferShape (SURVEY §2.1 #35).

Graph JSON save/load keeps the reference's format family
(nnvm::pass::SaveJSON: nodes/arg_nodes/heads) so checkpoints remain
inspectable by the same tooling.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import attribute, name as _name_mod
from .base import MXNetError
from .ops import OP_REGISTRY, OpContext, OpDef, get_op

# Monotonic id for ephemeral Symbol.grad ops (never reused, unlike id()).
_GRAD_OP_COUNTER = 0


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "misc_attrs")

    def __init__(self, op: Optional[OpDef], name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["_Node", int]], is_aux: bool = False,
                 misc_attrs: Optional[Dict[str, str]] = None):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.is_aux = is_aux  # variable node holding auxiliary (non-grad) state
        self.misc_attrs = misc_attrs or {}

    @property
    def is_var(self):
        return self.op is None


def _topo_order(out_entries) -> List[_Node]:
    order: List[_Node] = []
    visited = set()

    def visit(node):
        if id(node) in visited:
            return
        visited.add(id(node))
        for child, _ in node.inputs:
            visit(child)
        order.append(node)

    for node, _ in out_entries:
        visit(node)
    return order


class Symbol:
    def __init__(self, entries: List[Tuple[_Node, int]]):
        self._entries = list(entries)

    # --- introspection ----------------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def _nodes(self) -> List[_Node]:
        return _topo_order(self._entries)

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._nodes() if n.is_var and not n.is_aux]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._nodes() if n.is_var and n.is_aux]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._entries:
            if node.is_var:
                outs.append(node.name)
            else:
                onames = node.op.get_output_names(node.attrs)
                outs.append("%s_%s" % (node.name, onames[idx]))
        return outs

    def list_inputs(self):
        return [n.name for n in self._nodes() if n.is_var]

    def get_internals(self) -> "Symbol":
        entries = []
        for node in self._nodes():
            if node.is_var:
                entries.append((node, 0))
            else:
                for i in range(node.op.get_num_outputs(node.attrs)):
                    entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        """Grouped symbol of the output nodes' immediate inputs, in
        order; None for a pure-variable symbol (reference
        python/mxnet/symbol.py get_children / test_symbol.py
        test_symbol_children semantics). A multi-output node contributes
        its inputs ONCE, not per selected output."""
        entries = []
        seen = set()
        for node, _ in self._entries:
            if id(node) in seen:
                continue
            seen.add(id(node))
            entries.extend(node.inputs)
        if not entries:
            return None
        return Symbol(entries)

    def __reduce__(self):
        # op impls are closures (unpicklable); the versioned JSON schema
        # is the durable form, so pickle round-trips THROUGH it
        # (reference test_symbol.py test_symbol_pickle capability).
        # Ephemeral ops (grad()'s synthesized backward nodes) are not in
        # the registry, so their JSON could never load back — fail at
        # DUMP time, not in some later process with a corrupt blob.
        from .ops.registry import OP_REGISTRY

        for n in self._nodes():
            if not n.is_var and n.op.name not in OP_REGISTRY:
                raise MXNetError(
                    "cannot pickle symbol: op %r is not in the registry "
                    "(ephemeral gradient/internal node)" % n.op.name)
        return (load_json, (self.tojson(),))

    def __deepcopy__(self, memo):
        # without this, copy.deepcopy would fall back to __reduce_ex__
        # and route through the JSON schema (breaking ephemeral-op
        # symbols that the structural __copy__ handles fine)
        return self.__copy__()

    def __getitem__(self, index):
        if isinstance(index, str):
            outs = self.list_outputs()
            if index not in outs:
                raise MXNetError("cannot find output %r in %s" % (index, outs))
            index = outs.index(index)
        return Symbol([self._entries[index]])

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        return (self[i] for i in range(len(self._entries)))

    def attr(self, key):
        if len(self._entries) == 1:
            return self._entries[0][0].misc_attrs.get(key)
        return None

    def attr_dict(self):
        ret = {}
        for node in self._nodes():
            if node.misc_attrs:
                ret[node.name] = dict(node.misc_attrs)
        return ret

    def _set_attr(self, **kwargs):
        for node, _ in self._entries:
            node.misc_attrs.update(kwargs)

    # --- composition ------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: substitute variable nodes (reference Symbol compose)."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        mapping = {}
        if args:
            vars_in = [n for n in self._nodes() if n.is_var and not n.is_aux]
            for var, rep in zip(vars_in, args):
                mapping[id(var)] = rep._entries[0]
        for k, v in kwargs.items():
            for n in self._nodes():
                if n.is_var and n.name == k:
                    mapping[id(n)] = v._entries[0]
        for node in self._nodes():
            node.inputs = [
                mapping.get(id(child), (child, idx)) if child.is_var else (child, idx)
                for child, idx in node.inputs
            ]

    def __copy__(self):
        # deep copy of node graph
        memo: Dict[int, _Node] = {}

        def cp(node):
            if id(node) in memo:
                return memo[id(node)]
            nn = _Node(node.op, node.name, dict(node.attrs),
                       [], node.is_aux, dict(node.misc_attrs))
            memo[id(node)] = nn
            nn.inputs = [(cp(c), i) for c, i in node.inputs]
            return nn

        return Symbol([(cp(n), i) for n, i in self._entries])

    # --- arithmetic (creates broadcast graph nodes) -----------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create_symbol(get_op(op_name), [a, b], {}, None)
        attrs = {"scalar": float(other)}
        name = scalar_op if not reverse else scalar_op.replace("_", "_r", 1)
        return _create_symbol(get_op(name), [self], attrs, None)

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self._binop(-1.0, "broadcast_mul", "_mul_scalar")

    def grad(self, wrt):
        """Gradient symbol wrt the named arguments (reference symbol.py:
        1374-1397 documents this API but its C implementation is a stub —
        'currently not implemented'; jax.vjp makes it real here).

        Returns a Symbol with one output per name in ``wrt``: the gradient
        of the SUM of this symbol's outputs with respect to that argument.
        The gradient symbol takes the same arguments (and aux states) as
        ``self``."""
        from .ops.registry import OpDef

        wrt = [wrt] if isinstance(wrt, str) else list(wrt)
        base = self.__copy__()
        arg_names = base.list_arguments()
        aux_names = base.list_auxiliary_states()
        missing = [w for w in wrt if w not in arg_names]
        if missing:
            raise MXNetError("grad: unknown arguments %s (have %s)"
                             % (missing, arg_names))
        eval_fn = base.build_eval()
        n_args = len(arg_names)

        def impl(attrs, inputs, aux, ctx):
            arg_values = dict(zip(arg_names, inputs))
            aux_values = dict(zip(aux_names, aux))

            import builtins

            def f(g_values):
                av = dict(arg_values)
                av.update(g_values)
                outs, _ = eval_fn(av, aux_values, ctx.is_train, ctx.rng)
                # builtins.sum: `sum` is a generated op in this namespace
                return builtins.sum(jnp.sum(o) for o in outs)

            grads = jax.grad(f)({w: arg_values[w] for w in wrt})
            return tuple(grads[w] for w in wrt), ()

        gname = _name_mod.current().get(None, "grad")
        # Ephemeral op: NOT registered in the global OP_REGISTRY (symbol
        # nodes hold the OpDef object directly; registering would grow the
        # registry unboundedly and id()-based names can collide after GC).
        # Consequence: grad symbols cannot round-trip through tojson/load.
        global _GRAD_OP_COUNTER
        _GRAD_OP_COUNTER += 1
        opdef = OpDef(
            name="_grad_%s_%d" % (gname, _GRAD_OP_COUNTER),
            impl=impl,
            arg_names=tuple(arg_names),
            aux_names=tuple(aux_names),
            num_outputs=len(wrt),
            output_names=tuple("%s_grad" % w for w in wrt),
            needs_rng=True,
            uses_train=True,
            doc="Gradient of %r wrt %s (Symbol.grad; ephemeral op, "
                "not serializable via tojson/load)" % (gname, wrt),
        )
        inputs = [Variable(n) for n in arg_names]
        for n in aux_names:  # aux slots need is_aux variable nodes
            inputs.append(Symbol([(_Node(None, n, {}, [], is_aux=True), 0)]))
        return _create_symbol(opdef, inputs, {}, gname,
                              input_names=arg_names + aux_names)

    # --- inference --------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        res = self._infer(kwargs, partial=False)
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer(kwargs, partial=True)

    def infer_type(self, *args, **kwargs):
        """Dtype-only propagation (reference nnvm InferType): parameter
        dtypes follow the first known input dtype; Cast/creation ops set
        their own."""
        known = {k: np.dtype(v) for k, v in kwargs.items()}
        nodes = self._nodes()
        dt: Dict[Tuple[int, int], Any] = {}
        var_dt: Dict[str, Any] = {}
        for node in nodes:
            if not node.is_var:
                continue
            d = known.get(node.name)
            if d is None and "__dtype__" in node.misc_attrs:
                d = np.dtype(node.misc_attrs["__dtype__"])
            if d is not None:
                dt[(id(node), 0)] = d
                var_dt[node.name] = d
        for node in nodes:
            if node.is_var:
                continue
            in_dts = [dt.get((id(c), i)) for c, i in node.inputs]
            ref = next((d for d in in_dts if d is not None), None)
            own = node.attrs.get("dtype") if "dtype" in (node.attrs or {}) else None
            if ref is None and own is None:
                continue
            for (c, i), d in zip(node.inputs, in_dts):
                if d is None and ref is not None:
                    dt[(id(c), i)] = ref
                    if c.is_var:
                        var_dt[c.name] = ref
            out_d = np.dtype(own) if own else ref
            for i in range(node.op.get_num_outputs(node.attrs)):
                dt[(id(node), i)] = out_d
        arg_ts = [var_dt.get(n) for n in self.list_arguments()]
        aux_ts = [var_dt.get(n) for n in self.list_auxiliary_states()]
        out_ts = [dt.get((id(n), i)) for n, i in self._entries]
        return (arg_ts, out_ts, aux_ts)

    def _infer(self, known_shapes, partial):
        args_s, outs_s, aux_s, _ = self._infer_structs(known_shapes, {}, partial)
        return args_s, outs_s, aux_s

    def _infer_structs(self, known_shapes: Dict[str, tuple], known_dtypes: Dict[str, Any], partial: bool):
        """Propagate ShapeDtypeStructs through the graph."""
        known_shapes = {
            k: tuple(v) for k, v in known_shapes.items() if v is not None
        }
        env: Dict[Tuple[int, int], Any] = {}  # (node id, out idx) -> ShapeDtypeStruct
        var_struct: Dict[str, Any] = {}
        default_dtype = jnp.float32
        nodes = self._nodes()
        # seed variables with known shapes
        for node in nodes:
            if not node.is_var:
                continue
            shape = known_shapes.get(node.name)
            if shape is None and "__shape__" in node.misc_attrs:
                shape = tuple(json.loads(node.misc_attrs["__shape__"]))
            dtype = known_dtypes.get(node.name)
            if dtype is None and "__dtype__" in node.misc_attrs:
                dtype = np.dtype(node.misc_attrs["__dtype__"])
            if shape is not None:
                st = jax.ShapeDtypeStruct(shape, dtype or default_dtype)
                env[(id(node), 0)] = st
                var_struct[node.name] = st
            elif dtype is not None:
                var_struct[node.name] = jax.ShapeDtypeStruct((), dtype)

        for node in nodes:
            if node.is_var:
                continue
            op = node.op
            attrs = node.attrs
            in_structs = [env.get((id(c), i)) for c, i in node.inputs]
            n_aux = len(op.get_aux_names(attrs)) if not op.variadic else 0
            n_args = len(node.inputs) - n_aux
            # fill parameter shapes via the op's reverse rule
            rule = getattr(op, "infer_params", None)
            if rule is not None:
                shapes = [None if s is None else tuple(s.shape) for s in in_structs]
                shapes = rule(attrs, shapes)
                ref_dtype = next((s.dtype for s in in_structs if s is not None), default_dtype)
                for i, (s, st) in enumerate(zip(shapes, in_structs)):
                    if st is None and s is not None:
                        child, cidx = node.inputs[i]
                        new_st = jax.ShapeDtypeStruct(tuple(s), ref_dtype)
                        env[(id(child), cidx)] = new_st
                        if child.is_var:
                            var_struct[child.name] = new_st
                in_structs = [env.get((id(c), i)) for c, i in node.inputs]
            if any(s is None for s in in_structs):
                if partial:
                    continue
                missing = [
                    node.inputs[i][0].name for i, s in enumerate(in_structs) if s is None
                ]
                raise MXNetError(
                    "infer_shape: cannot infer inputs %s of node %s; provide their shapes"
                    % (missing, node.name)
                )
            ins = in_structs[:n_args]
            auxs = in_structs[n_args:]

            def fn(*flat):
                i_ = flat[: len(ins)]
                a_ = flat[len(ins):]
                outs, _ = op.impl(attrs, i_, a_, OpContext(False, jax.random.PRNGKey(0)))
                return outs

            try:
                out_structs = jax.eval_shape(fn, *(list(ins) + list(auxs)))
            except Exception as e:  # surface with node context
                raise MXNetError(
                    "shape inference failed at node %s (%s): %s" % (node.name, op.name, e)
                ) from e
            for i, st in enumerate(out_structs):
                env[(id(node), i)] = jax.ShapeDtypeStruct(tuple(st.shape), st.dtype)

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        args_shapes = [
            (tuple(var_struct[n].shape) if n in var_struct else None) for n in arg_names
        ]
        aux_shapes = [
            (tuple(var_struct[n].shape) if n in var_struct else None) for n in aux_names
        ]
        out_shapes = []
        out_structs_list = []
        for node, idx in self._entries:
            st = env.get((id(node), idx))
            out_shapes.append(None if st is None else tuple(st.shape))
            out_structs_list.append(st)
        structs = {
            "args": {n: var_struct.get(n) for n in arg_names},
            "aux": {n: var_struct.get(n) for n in aux_names},
            "outs": out_structs_list,
        }
        if not partial and any(s is None for s in args_shapes + out_shapes + aux_shapes):
            raise MXNetError("infer_shape: incomplete inference; missing shapes")
        return args_shapes, out_shapes, aux_shapes, structs

    # --- binding ----------------------------------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None, compute_dtype=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        compute_dtype=compute_dtype)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, compute_dtype=None, **kwargs):
        """Infer shapes from kwargs, allocate arrays, bind (reference
        python/mxnet/symbol.py:1117)."""
        from . import ndarray as nd
        from .executor import Executor

        type_dict = type_dict or {}
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        _, _, _, structs = self._infer_structs(kwargs, {k: np.dtype(v) for k, v in type_dict.items()}, partial=False)
        args = {}
        for n, shp in zip(arg_names, arg_shapes):
            st = structs["args"][n]
            args[n] = nd.zeros(shp, ctx=ctx, dtype=str(st.dtype))
        args_grad = None
        if grad_req != "null":
            args_grad = {
                n: nd.zeros(a.shape, ctx=ctx, dtype=str(structs["args"][n].dtype))
                for n, a in args.items()
            }
        aux_states = {
            n: nd.zeros(shp, ctx=ctx, dtype=str(structs["aux"][n].dtype))
            for n, shp in zip(aux_names, aux_shapes)
        }
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        compute_dtype=compute_dtype)

    # --- evaluation helper used by Executor -------------------------------
    def build_eval(self, remat_segments=None):
        """Return fn(arg_values: dict, aux_values: dict, is_train, rng)
        -> (outputs list, aux_updates dict). Pure; jit-able.

        remat_segments > 1 partitions the graph into that many contiguous
        topological segments, each wrapped in ``jax.checkpoint``: backward
        keeps only segment-boundary activations and recomputes segment
        interiors — the reference's MXNET_BACKWARD_DO_MIRROR /
        note_memory.md "memonger" memory-for-FLOPs trade
        (graph_executor.cc:213-226), realized the TPU way. ``None`` reads
        the MXNET_BACKWARD_DO_MIRROR env var (1 = auto ≈ sqrt(#ops),
        k>1 = exactly k segments).

        MXNET_CONV_LAYOUT=nhwc (default; read here, like the mirror
        flag) additionally runs the conv backbone as NHWC layout islands
        (ops/layout.py): convs seed islands, layout-agnostic neighbours
        propagate them, anything else transposes back — so the rewrite
        is local to this evaluator and the graph/API stay NCHW."""
        from .ops import layout as _oplayout

        nhwc = _oplayout.enabled()
        nodes = self._nodes()
        entries = self._entries
        if remat_segments is None:
            import builtins
            import math
            import os as _os

            flag = int(_os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") or 0)
            # `sum`/`max` here are generated op functions, not builtins
            n_ops = builtins.sum(1 for n in nodes if not n.is_var)
            remat_segments = (builtins.max(2, int(math.sqrt(n_ops)))
                              if flag == 1 else flag)
        if remat_segments and remat_segments > 1:
            return self._build_eval_segmented(nodes, entries,
                                              int(remat_segments))

        def eval_fn(arg_values, aux_values, is_train, rng):
            env: Dict[Tuple[int, int], Any] = {}
            tags = set()  # env keys whose value is resident NHWC
            aux_updates: Dict[str, Any] = {}
            for ni, node in enumerate(nodes):
                if node.is_var:
                    src = aux_values if node.is_aux else arg_values
                    if node.name not in src:
                        raise MXNetError("missing value for %s" % node.name)
                    env[(id(node), 0)] = src[node.name]
                    continue
                op = node.op
                attrs = node.attrs
                vals = [env[(id(c), i)] for c, i in node.inputs]
                n_aux = len(op.get_aux_names(attrs)) if not op.variadic else 0
                n_args = len(vals) - n_aux
                tagged_out = ()
                if nhwc:
                    attrs, vals, tagged_out = _oplayout.adapt(
                        op.name, attrs, vals,
                        [(id(c), i) in tags for c, i in node.inputs])
                node_rng = None
                if op.needs_rng:
                    node_rng = jax.random.fold_in(rng, ni)
                outs, aux_out = op.impl(
                    attrs, tuple(vals[:n_args]), tuple(vals[n_args:]),
                    OpContext(is_train, node_rng),
                )
                for i, o in enumerate(outs):
                    env[(id(node), i)] = o
                    if i in tagged_out:
                        tags.add((id(node), i))
                for (child, _), new in zip(node.inputs[n_args:], aux_out):
                    if child.is_var:
                        aux_updates[child.name] = new
            outputs = [(_oplayout.to_nchw(env[(id(n), i)])
                        if (id(n), i) in tags else env[(id(n), i)])
                       for n, i in entries]
            return outputs, aux_updates

        return eval_fn

    def _build_eval_segmented(self, nodes, entries, n_segments):
        """Segmented evaluator: contiguous topo chunks, each under
        jax.checkpoint; only chunk-boundary values are saved for backward.

        NHWC layout islands (MXNET_CONV_LAYOUT, ops/layout.py) span
        chunk boundaries: the tag set lives in the evaluator scope, so a
        value that leaves one chunk resident-NHWC enters the next one
        tagged — the per-conv layouts (and therefore the numerics) match
        the unsegmented evaluator exactly, and jax.checkpoint simply
        stores the NHWC boundary value. The retrace during backward
        re-derives the same tags (the pass is deterministic)."""
        import math

        import builtins

        from .ops import layout as _oplayout

        nhwc = _oplayout.enabled()

        op_nodes = [(ni, n) for ni, n in enumerate(nodes) if not n.is_var]
        # `min`/`max`/`sum` are generated op functions in this namespace
        k = builtins.max(1, builtins.min(n_segments, len(op_nodes)))
        per = math.ceil(len(op_nodes) / k)
        chunks = [op_nodes[i * per:(i + 1) * per]
                  for i in range(k) if op_nodes[i * per:(i + 1) * per]]
        final_keys = {(id(n), i) for n, i in entries}
        # per-chunk: which produced entries must leave the chunk (consumed
        # by a LATER chunk or part of the final outputs)
        out_keys = []
        for ci, chunk in enumerate(chunks):
            produced = {(id(n), i) for _, n in chunk
                        for i in range(n.op.get_num_outputs(n.attrs))}
            needed = set()
            for cj in range(ci + 1, len(chunks)):
                for _, n in chunks[cj]:
                    for c, i in n.inputs:
                        if (id(c), i) in produced:
                            needed.add((id(c), i))
            needed |= produced & final_keys
            out_keys.append(sorted(needed, key=lambda t: (t[0], t[1])))
        in_keys = []
        for ci, chunk in enumerate(chunks):
            produced = {(id(n), i) for _, n in chunk
                        for i in range(n.op.get_num_outputs(n.attrs))}
            needed = {(id(c), i) for _, n in chunk for c, i in n.inputs
                      if (id(c), i) not in produced}
            in_keys.append(sorted(needed, key=lambda t: (t[0], t[1])))

        def eval_fn(arg_values, aux_values, is_train, rng):
            env: Dict[Tuple[int, int], Any] = {}
            tags = set()  # NHWC-resident keys, shared across chunks
            aux_updates: Dict[str, Any] = {}
            for node in nodes:
                if node.is_var:
                    src = aux_values if node.is_aux else arg_values
                    if node.name not in src:
                        raise MXNetError("missing value for %s" % node.name)
                    env[(id(node), 0)] = src[node.name]

            for ci, chunk in enumerate(chunks):
                ikeys, okeys = in_keys[ci], out_keys[ci]

                def chunk_fn(in_vals, c_rng, _chunk=chunk, _ik=ikeys,
                             _ok=okeys):
                    local = dict(zip(_ik, in_vals))
                    aux_out_items = []
                    for ni, node in _chunk:
                        op, attrs = node.op, node.attrs
                        vals = [local[(id(c), i)] for c, i in node.inputs]
                        n_aux = (len(op.get_aux_names(attrs))
                                 if not op.variadic else 0)
                        n_args = len(vals) - n_aux
                        tagged_out = ()
                        if nhwc:
                            attrs, vals, tagged_out = _oplayout.adapt(
                                op.name, attrs, vals,
                                [(id(c), i) in tags for c, i in node.inputs])
                        node_rng = (jax.random.fold_in(c_rng, ni)
                                    if op.needs_rng else None)
                        outs, aux_out = op.impl(
                            attrs, tuple(vals[:n_args]), tuple(vals[n_args:]),
                            OpContext(is_train, node_rng))
                        for i, o in enumerate(outs):
                            local[(id(node), i)] = o
                            if i in tagged_out:
                                tags.add((id(node), i))
                        for (child, _), new in zip(node.inputs[n_args:],
                                                   aux_out):
                            if child.is_var:
                                aux_out_items.append((child.name, new))
                    return (tuple(local[kk] for kk in _ok),
                            tuple(v for _, v in aux_out_items))

                aux_names_chunk = []
                for ni, node in chunk:
                    op, attrs = node.op, node.attrs
                    n_aux = (len(op.get_aux_names(attrs))
                             if not op.variadic else 0)
                    if n_aux:
                        for child, _ in node.inputs[-n_aux:]:
                            if child.is_var:
                                aux_names_chunk.append(child.name)
                # last chunk needs no checkpoint: its residuals are the
                # final outputs anyway
                fn = (jax.checkpoint(chunk_fn)
                      if ci < len(chunks) - 1 else chunk_fn)
                in_vals = tuple(env[kk] for kk in ikeys)
                out_vals, aux_vals = fn(in_vals, rng)
                env.update(zip(okeys, out_vals))
                aux_updates.update(zip(aux_names_chunk, aux_vals))

            outputs = [env[(id(n), i)] for n, i in entries]
            return outputs, aux_updates

        return eval_fn

    # --- save / load ------------------------------------------------------
    def tojson(self, format: str = "native") -> str:
        """Serialize the graph. format="native" (default) is this
        repo's schema; format="reference" emits the reference
        framework's nodes/arg_nodes/heads symbol JSON
        (interop.save_symbol_json — readable by the reference era and
        by this repo's own reader, the write-side complement of the
        read interop)."""
        if format == "reference":
            from . import interop

            return interop.save_symbol_json(self)
        if format != "native":
            raise ValueError("unknown symbol JSON format %r" % (format,))
        nodes = self._nodes()
        idx = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append(
                {
                    "op": "null" if n.is_var else n.op.name,
                    "name": n.name,
                    # None serializes as "null" (the enum spelling the
                    # loader's coerce_attr maps back to None), so
                    # save->load->save is byte-stable
                    "attrs": {k: ("null" if v is None
                                  else repr(v) if not isinstance(v, str)
                                  else v)
                              for k, v in n.attrs.items()},
                    "inputs": [[idx[id(c)], i, 0] for c, i in n.inputs],
                    "is_aux": bool(n.is_aux),
                    "misc_attrs": n.misc_attrs,
                }
            )
        heads = [[idx[id(n)], i, 0] for n, i in self._entries]
        return json.dumps(
            {
                "nodes": jnodes,
                "arg_nodes": [i for i, n in enumerate(nodes) if n.is_var],
                "heads": heads,
                "attrs": {"mxnet_tpu_version": 1},
            },
            indent=2,
        )

    def save(self, fname: str, format: str = "native"):
        with open(fname, "w") as f:
            f.write(self.tojson(format=format))

    def debug_str(self):
        lines = []
        for n in self._nodes():
            if n.is_var:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (c.name, i) for c, i in n.inputs)
                lines.append("%s(%s) name=%s attrs=%s" % (n.op.name, ins, n.name, n.attrs))
        return "\n".join(lines)


def load_json(json_str: str) -> Symbol:
    from .base import coerce_attr

    data = json.loads(json_str)
    from . import interop
    if interop.is_reference_symbol_json(data):
        # a reference-ecosystem symbol dump (any legacy version):
        # interop.py applies the upgrade semantics of the reference's
        # legacy_json_util.cc
        return interop.load_symbol_json(data)
    nodes: List[_Node] = []
    for jn in data["nodes"]:
        if jn["op"] == "null":
            node = _Node(None, jn["name"], {}, [], jn.get("is_aux", False), jn.get("misc_attrs", {}))
        else:
            op = get_op(jn["op"])
            attrs = {k: coerce_attr(v) for k, v in jn.get("attrs", {}).items()}
            attrs = op.parse_attrs(attrs)
            inputs = [(nodes[i], oi) for i, oi, _ in jn["inputs"]]
            node = _Node(op, jn["name"], attrs, inputs, False, jn.get("misc_attrs", {}))
        nodes.append(node)
    entries = [(nodes[i], oi) for i, oi, _ in data["heads"]]
    return Symbol(entries)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs) -> Symbol:
    """Create a variable symbol (reference symbol.py Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    misc = attribute.current().get(attr or {})
    if shape is not None:
        misc["__shape__"] = json.dumps(list(shape))
    if dtype is not None:
        misc["__dtype__"] = str(np.dtype(dtype))
    if lr_mult is not None:
        misc["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        misc["__wd_mult__"] = str(wd_mult)
    if init is not None:
        misc["__init__"] = init if isinstance(init, str) else init.dumps()
    for k, v in kwargs.items():
        misc[k] = str(v)
    node = _Node(None, name, {}, [], False, misc)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def zeros(shape, dtype="float32", **kwargs):
    return _create_symbol(get_op("_zeros"), [], {"shape": shape, "dtype": dtype}, kwargs.get("name"))


def ones(shape, dtype="float32", **kwargs):
    return _create_symbol(get_op("_ones"), [], {"shape": shape, "dtype": dtype}, kwargs.get("name"))


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", **kwargs):
    return _create_symbol(
        get_op("_arange"),
        [],
        {"start": start, "stop": stop, "step": step, "repeat": repeat, "dtype": dtype},
        kwargs.get("name"),
    )


def _create_symbol(op: OpDef, input_syms: List[Symbol], attrs: Dict[str, Any],
                   name: Optional[str], input_names: Optional[List[str]] = None) -> Symbol:
    parsed = op.parse_attrs(attrs)
    hint = (op.py_name or op.name).lower().lstrip("_")
    node_name = _name_mod.current().get(name, hint)
    arg_names = list(op.get_arg_names(parsed))
    aux_names = list(op.get_aux_names(parsed))
    entries: List[Tuple[_Node, int]] = []
    if op.variadic:
        for s in input_syms:
            entries.append(s._entries[0])
    else:
        given = {}
        if input_names:
            for n, s in zip(input_names, input_syms):
                given[n] = s
        else:
            for n, s in zip(arg_names + aux_names, input_syms):
                given[n] = s
        for n in arg_names + aux_names:
            if n in given and given[n] is not None:
                entries.append(given[n]._entries[0])
            else:
                # auto-create the parameter variable (reference: NNVM compose
                # creates missing inputs named <node>_<arg>)
                vnode = _Node(None, "%s_%s" % (node_name, n), {}, [],
                              is_aux=(n in aux_names),
                              misc_attrs=attribute.current().get({}))
                entries.append((vnode, 0))
    # mark aux variables
    node = _Node(op, node_name, parsed, entries, False, attribute.current().get({}))
    nout = op.get_num_outputs(parsed)
    return Symbol([(node, i) for i in range(nout)])


def _make_sym_function(op: OpDef):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_kwargs = {}
        attrs = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attrs[k] = v
        parsed = op.parse_attrs(attrs)
        if op.variadic:
            inputs = list(args) + [sym_kwargs[k] for k in sorted(sym_kwargs)]
            s = _create_symbol(op, inputs, attrs, name)
        else:
            names = list(op.get_arg_names(parsed)) + list(op.get_aux_names(parsed))
            ordered: List[Optional[Symbol]] = [None] * len(names)
            for i, a in enumerate(args):
                ordered[i] = a
            for k, v in sym_kwargs.items():
                if k not in names:
                    raise MXNetError("%s: unexpected input %r" % (op.name, k))
                ordered[names.index(k)] = v
            s = _create_symbol(op, ordered, attrs, name, input_names=names)
        if attr:
            s._set_attr(**attr)
        return s

    fn.__name__ = op.py_name or op.name
    fn.__doc__ = op.build_doc()
    return fn


def _populate_namespace():
    g = globals()
    seen = {}
    for rname, op in OP_REGISTRY.items():
        if id(op) in seen:
            target = seen[id(op)]
        else:
            target = _make_sym_function(op)
            seen[id(op)] = target
        if rname not in g:
            g[rname] = target
        pub = op.py_name or rname
        if pub not in g:
            g[pub] = target


_populate_namespace()
