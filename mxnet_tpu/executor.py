"""Graph executor.

TPU-native analogue of src/executor/graph_executor.{h,cc} +
include/mxnet/executor.h:34-104. Where the reference builds per-node engine
ops with memory planning and bulk segments, this executor compiles the WHOLE
symbolic graph into:

- one jitted forward computation  (Forward,  graph_executor.cc:32), and
- one jitted forward+backward computation (Backward, graph_executor.cc:45),
  derived with jax.vjp — the analogue of nnvm::pass::Gradient
  (graph_executor.cc:233) — with grad_req write/add/null semantics
  (OpReqType, operator.h:24-37) applied in-graph. `add` accumulation donates
  the old gradient buffer so XLA updates it in place (kAddTo ≡ donation).

Memory planning, inplace reuse, and op fusion are XLA's buffer assignment —
the PlanMemory/DetectInplaceAddTo passes have no hand-written counterpart
here by design (SURVEY §7 translation table).

The optional `shared_exec` reuses argument/grad buffers across executors
(bucketing support, graph_executor.cc:452-564 shared pools).
"""
from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import progcache as _progcache
from . import random as _random
from . import telemetry as _telemetry
from .analysis import compile_witness as _witness
from .base import MXNetError
from .context import Context, default_context
from .ndarray import NDArray


def _cast_floats(tree, dtype, src=None):
    """Cast float leaves of a list/dict tree to dtype (inside jit, so XLA
    fuses the converts into neighbouring ops). Only leaves of dtype `src`
    (default float32) are touched, so integer/bool leaves pass through."""
    src = jnp.float32 if src is None else jnp.dtype(src)

    def cast(v):
        if hasattr(v, "dtype") and v.dtype == src:
            return v.astype(dtype)
        return v
    return jax.tree_util.tree_map(cast, tree)


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 compute_dtype=None):
        """compute_dtype: optional low-precision compute dtype ("bfloat16").
        Mixed precision the TPU-native way: parameters, gradients, and
        optimizer state stay float32 (master weights); inside the single
        jitted graph all float32 leaves are cast to compute_dtype so matmuls
        and convs hit the MXU's bf16 path, and outputs/gradients are cast
        back to float32. This is the analogue of the reference's fp16
        training path (Cast ops + float16 data, tests/python/train/
        test_dtype.py) — bf16 needs no loss scaling, unlike fp16.
        Default from MXNET_COMPUTE_DTYPE env var."""
        self._symbol = symbol
        if compute_dtype is None:
            compute_dtype = os.environ.get("MXNET_COMPUTE_DTYPE") or None
        self._compute_dtype = (jnp.dtype(compute_dtype)
                               if compute_dtype not in (None, "", "float32")
                               else None)
        self._ctx = ctx if isinstance(ctx, Context) else (ctx[0] if ctx else default_context())
        self._group2ctx = group2ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        # ---- normalize args into name->NDArray dicts
        self.arg_dict: Dict[str, NDArray] = self._to_dict(args, arg_names, "args")
        if shared_exec is not None:
            # share buffers with the master executor (bucketing)
            for n in arg_names:
                if n in shared_exec.arg_dict and shared_exec.arg_dict[n].shape == self.arg_dict[n].shape:
                    self.arg_dict[n] = shared_exec.arg_dict[n]
        self.aux_dict: Dict[str, NDArray] = self._to_dict(aux_states or {}, aux_names, "aux")

        # ---- grad_req per-arg
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        if args_grad is None:
            self.grad_dict: Dict[str, NDArray] = {}
        else:
            self.grad_dict = self._to_dict(args_grad, arg_names, "args_grad", allow_missing=True)
        if shared_exec is not None:
            for n, g in shared_exec.grad_dict.items():
                if n in self.grad_dict and g.shape == self.grad_dict[n].shape:
                    self.grad_dict[n] = g
        for n in arg_names:
            if self.grad_req.get(n, "null") != "null" and n not in self.grad_dict:
                self.grad_req[n] = "null"

        self._arg_names = arg_names
        self._aux_names = aux_names
        self._eval_fn = symbol.build_eval()
        self._fwd_cache: Dict[bool, Any] = {}
        self._bwd_fn = None
        self._fwd_bwd_fn = None
        self.outputs: List[NDArray] = []
        self._monitor_cb = None
        self._monitored_rng = None
        self._rng_counter = 0
        self._last_rng = None
        self._graph_needs_rng = None  # computed lazily on first use

    @staticmethod
    def _to_dict(values, names, what, allow_missing=False):
        if values is None:
            values = {}
        if isinstance(values, dict):
            out = dict(values)
        else:
            values = list(values)
            if len(values) != len(names) and not allow_missing:
                raise MXNetError(
                    "%s: expected %d entries, got %d" % (what, len(names), len(values))
                )
            out = {n: v for n, v in zip(names, values) if v is not None}
        missing = [n for n in names if n not in out]
        if missing and not allow_missing and what != "args_grad":
            raise MXNetError("%s missing entries for %s" % (what, missing))
        return out

    # --- compiled paths ---------------------------------------------------
    def _get_fwd(self, is_train: bool):
        fn = self._fwd_cache.get(is_train)
        if fn is None:
            eval_fn = self._eval_fn
            cd = self._compute_dtype

            def fwd(arg_values, aux_values, rng):
                if cd is not None:
                    arg_values = _cast_floats(arg_values, cd)
                    aux_values = _cast_floats(aux_values, cd)
                outs, aux_up = eval_fn(arg_values, aux_values, is_train, rng)
                if cd is not None:
                    outs = _cast_floats(outs, jnp.float32, src=cd)
                    aux_up = _cast_floats(aux_up, jnp.float32, src=cd)
                return outs, aux_up

            fn = jax.jit(fwd)
            self._fwd_cache[is_train] = fn
        return fn

    def _get_fwd_bwd(self):
        """Fused forward+backward — ONE XLA computation for the whole
        training step graph (north-star: single HLO per symbolic subgraph)."""
        if self._fwd_bwd_fn is None:
            eval_fn = self._eval_fn
            grad_names = [n for n in self._arg_names if self.grad_req.get(n) != "null"]
            reqs = tuple(self.grad_req[n] for n in grad_names)

            cd = self._compute_dtype

            def fwd_bwd(arg_values, aux_values, rng, head_grads, old_grads):
                grad_vals = [arg_values[n] for n in grad_names]

                def f(*gvals):
                    av = dict(arg_values)
                    for n, v in zip(grad_names, gvals):
                        av[n] = v
                    auxv = aux_values
                    if cd is not None:
                        # bf16 compute; vjp of the cast returns f32 grads
                        # (transpose of convert_element_type casts back).
                        av = _cast_floats(av, cd)
                        auxv = _cast_floats(auxv, cd)
                    outs, aux_up = eval_fn(av, auxv, True, rng)
                    if cd is not None:
                        outs = _cast_floats(outs, jnp.float32, src=cd)
                        aux_up = _cast_floats(aux_up, jnp.float32, src=cd)
                    return outs, aux_up

                (outs, aux_up), vjp = jax.vjp(lambda *g: f(*g), *grad_vals, has_aux=False)
                if head_grads is None:
                    head_grads = [jnp.ones_like(o) for o in outs]
                grads = vjp((list(head_grads), {k: jnp.zeros_like(v) for k, v in aux_up.items()}))
                new_grads = []
                for n, g, req in zip(grad_names, grads, reqs):
                    # old_grads holds ONLY add-req buffers; write-req grads
                    # need no host-side zeros (kAddTo vs kWriteTo)
                    new_grads.append(old_grads[n] + g if req == "add" else g)
                return outs, aux_up, new_grads

            self._fwd_bwd_fn = jax.jit(fwd_bwd, donate_argnums=(4,))
            self._grad_names = grad_names
        return self._fwd_bwd_fn

    def make_train_step(self, update_fn, chain=1, mesh=None, shard_axis="data"):
        """Build ONE jitted computation for a whole training step:
        forward + backward + optimizer update, with parameter and
        optimizer-state buffers donated so XLA updates them in place.

        This is the full-fusion analogue of the reference's bulk segment
        execution (graph_executor.cc:681-759 batches ops into one engine op;
        here the step — including the update the reference runs as separate
        fused optimizer kernels, optimizer_op.cc — is a single XLA program,
        so per-step host work is one dispatch and one pytree flatten).

        update_fn(params, grads, states, *extra) -> (new_params, new_states)
        must be pure/traceable (e.g. built from optimizer.create's update
        rule); extra positional args to step() are forwarded to it as traced
        values (dynamic lr/wd arrays and the like).
        Returns step(params, states, data_values: dict) ->
        (outputs, new_params, new_states). `params` covers the grad-bearing
        args; `data_values` the rest (data/label). Aux states (BN stats) are
        threaded internally and updated in place on self.aux_dict.

        DONATION CONTRACT: the params/states passed to step() are consumed
        (their device buffers are reused for the outputs — kWriteInplace).
        Do not alias them with live NDArrays; thread the returned values
        into the next call.

        ``chain`` > 1 runs that many optimizer steps (same feed) inside
        ONE device program via lax.scan — the bulk-execution analogue
        for dispatch-bound loops (each Python dispatch costs ~1.4 ms of
        device idle on the dev chip; chaining amortizes it to 1/chain).
        Aux states (BN stats) thread through the scan carry.

        On TPU the step additionally compiles with AUTO input/output
        layouts for params/states (jax.experimental.layout): without
        this, XLA keeps the f32 master weights in the row-major entry
        layout and inserts per-step layout copies around every conv
        weight's use and update (~1 ms/step at bs128 — measured via
        tools/profile_step.py, the 214 anonymous data-formatting
        copies). The first call relayouts the caller's arrays once;
        returned params stay in the chosen layouts thereafter.
        MXNET_STEP_AUTO_LAYOUT=0 disables.

        ``mesh``: a jax Mesh with a data-parallel axis ``shard_axis``.
        When its size is > 1, MXNET_SHARDED_UPDATE picks the ZeRO stage
        (Xu et al., PAPERS.md; docs/parallelism.md "ZeRO-2/3"). Stage 1
        (default): f32 master weights and optimizer state live
        1/N-sharded across the data axis, gradients are reduce-scattered
        onto the shards, each replica updates only its shard, and the
        new weights are all-gathered for the next forward. Stage 2
        additionally scatters each gradient bucket at its producer site
        as backward emits it (zero2_grad_scatter — full gradients never
        materialize). Stage 3 additionally keeps the parameters sharded
        THROUGH the step: leaves all-gather on demand in forward and
        re-gather in backward (zero3_gather + zero3_remat), so
        param+grad+opt bytes/chip are all ~1/N. Every stage is expressed
        as sharding constraints inside the ONE donated program, so XLA's
        SPMD partitioner places (and overlaps) the collectives; 0 opts
        out. The first call commits params/states to the sharded layout;
        returned values stay sharded, so thread them back in as usual.
        """
        eval_fn = self._eval_fn
        grad_names = list(self._grad_names_list())
        data_names = [n for n in self._arg_names if n not in set(grad_names)]
        cd = self._compute_dtype
        chain = max(1, int(chain))
        from .parallel import collectives as _coll
        stage = _coll.sharded_stage(mesh, shard_axis)
        sharded = stage >= 1

        def one_step(params, states, aux_values, rng, data_values, *extra):
            # Stage 1/2: params arrive 1/N-sharded; gather the whole tree
            # replicated up front for forward/backward (vjp's transpose of
            # the gather, fused with the data-parallel psum, is exactly
            # reduce_scatter). Stage 3 differentiates the SHARDED tree
            # directly: each leaf is gathered on demand inside `f` and
            # re-gathered in backward (zero3_remat drops the gathered
            # copies from the residuals), so full weights are transient.
            arg = (_coll.replicate_constrain(params, mesh)
                   if sharded and stage < 3 else params)

            def f(p):
                full = (_coll.zero3_gather(p, mesh, shard_axis)
                        if stage >= 3 else p)
                if stage >= 2:
                    # ZeRO-2: backward emits reduce-scattered gradient
                    # shards bucket-by-bucket as it runs (overlapping the
                    # remaining backward compute) instead of materializing
                    # the full gradient tree first
                    full = _coll.zero2_grad_scatter(full, mesh, shard_axis)
                av = dict(data_values)
                av.update(full)
                auxv = aux_values
                if cd is not None:
                    av = _cast_floats(av, cd)
                    auxv = _cast_floats(auxv, cd)
                outs, aux_up = eval_fn(av, auxv, True, rng)
                if cd is not None:
                    outs = _cast_floats(outs, jnp.float32, src=cd)
                    aux_up = _cast_floats(aux_up, jnp.float32, src=cd)
                return outs, aux_up

            fd = _coll.zero3_remat(f) if stage >= 3 else f
            (outs, aux_up), vjp = jax.vjp(fd, arg)
            (grads,) = vjp(([jnp.ones_like(o) for o in outs],
                            {k: jnp.zeros_like(v) for k, v in aux_up.items()}))
            if sharded:
                grads = _coll.zero1_constrain(grads, mesh, shard_axis)
            new_params, new_states = update_fn(params, grads, states, *extra)
            if sharded:
                new_params = _coll.zero1_constrain(new_params, mesh,
                                                   shard_axis)
                new_states = _coll.zero1_constrain(new_states, mesh,
                                                   shard_axis)
            return outs, new_params, new_states, aux_up

        if chain == 1:
            step = one_step
        else:
            def step(params, states, aux_values, rng, data_values, *extra):
                def body(carry, sub_rng):
                    p, s, aux = carry
                    outs, p, s, aux = one_step(p, s, aux, sub_rng,
                                               data_values, *extra)
                    return (p, s, aux), outs

                keys = jax.random.split(rng, chain)
                (p, s, aux), outs_seq = jax.lax.scan(
                    body, (params, states, aux_values), keys)
                outs = [o[-1] for o in outs_seq]  # last sub-step's outputs
                return outs, p, s, aux

        # gate on THIS executor's device, not the process default backend:
        # a cpu-context Module in a tpu-default process (mixed setups,
        # CPU data workers next to a chip) must not route cpu arrays
        # through the TPU-only AUTO-layout compile
        use_auto = (self._ctx.device_type in ("tpu", "gpu")
                    and jax.default_backend() == "tpu"
                    and os.environ.get(
                        "MXNET_STEP_AUTO_LAYOUT", "1") != "0")
        jitted = None if use_auto else jax.jit(step, donate_argnums=(0, 1))
        aot = {}  # compiled, in_formats, placed (built on first call)

        def _run_impl(params, states, data_values, *extra):
            rng = self._next_rng()
            aux_values = {n: a._data for n, a in self.aux_dict.items()}
            dv = {n: (v._data if isinstance(v, NDArray) else jnp.asarray(v))
                  for n, v in data_values.items()}
            for n in data_names:
                if n not in dv and n in self.arg_dict:
                    dv[n] = self.arg_dict[n]._data
            if sharded and not aot.get("placed"):
                # first bind: materialize master weights + optimizer state
                # directly in the 1/N ZeRO layout (never
                # replicated-then-sliced); returned values keep it, so
                # this runs once
                params = _coll.zero1_place(params, mesh, shard_axis)
                states = _coll.zero1_place(states, mesh, shard_axis)
                aot["placed"] = True
            if not aot.get("gauges"):
                # per-chip byte gauges, one series per ZeRO stage:
                # param/grad from the stage's layout contract
                # (collectives.stage_train_bytes — gradients are
                # in-program transients XLA never exposes), opt measured
                # from the live optimizer-state tree
                n_sh = (int(dict(mesh.shape).get(shard_axis, 1))
                        if mesh is not None else 1)
                pb, gb = _coll.stage_train_bytes(
                    params, stage, max(1, n_sh), shard_axis)
                lbl = {"stage": str(stage)}
                _telemetry.registry.gauge(
                    "train_param_bytes", labels=lbl,
                    help="per-chip parameter bytes held through one train "
                         "step (layout-implied)").set(pb)
                _telemetry.registry.gauge(
                    "train_grad_bytes", labels=lbl,
                    help="per-chip gradient bytes at the reduction "
                         "boundary (layout-implied)").set(gb)
                _telemetry.registry.gauge(
                    "train_opt_bytes", labels=lbl,
                    help="per-chip optimizer-state bytes at rest "
                         "(measured)").set(_coll.per_device_bytes(states))
                aot["gather_bytes"] = sum(
                    int(a.size * jnp.dtype(a.dtype).itemsize)
                    for a in jax.tree_util.tree_leaves(params))
                aot["gauges"] = True
            if use_auto:
                if not aot.get("informats"):
                    from jax.experimental.layout import Format, Layout

                    def spec(tree):
                        # AUTO only for >=2D leaves (conv/fc weights —
                        # where the per-step layout copies live); small
                        # vectors keep the default layout (XLA's chosen
                        # exotic vector tilings break the tunneled
                        # backend's donation path). Under the ZeRO-1
                        # sharded update the Format also pins each
                        # leaf's NamedSharding so the learned layouts
                        # apply to the 1/N shards.
                        def one(a):
                            if sharded:
                                sh = _coll.zero1_sharding(
                                    mesh, a.shape, shard_axis)
                                return (Format(Layout.AUTO, sh)
                                        if a.ndim >= 2 else sh)
                            return Format(Layout.AUTO) if a.ndim >= 2 else None
                        return jax.tree_util.tree_map(one, tree)

                    nextra = (None,) * len(extra)
                    pspec, sspec = spec(params), spec(states)
                    jf = jax.jit(
                        step, donate_argnums=(0, 1),
                        in_shardings=(pspec, sspec, None, None, None)
                        + nextra,
                        out_shardings=(None, pspec, sspec, None))
                    # phase 1: compile once with AUTO to LEARN the
                    # copy-free layouts. jax's AOT Compiled __call__
                    # costs ~5 ms/dispatch of Python argument processing
                    # through the tunnel, so for UNchained steps
                    # (dispatch-per-step) phase 2 re-jits with the
                    # CONCRETE learned formats to stay on jit's fast
                    # cached dispatch path (~1.4 ms); with chain > 1 the
                    # dispatch cost is already amortized and the second
                    # (expensive, scan-of-steps) compile isn't worth it.
                    learned = jf.lower(params, states, aux_values, rng,
                                       dv, *extra).compile()
                    _witness.record_compile("train_step",
                                            key="auto_layout")
                    pf, sf = (learned.input_formats[0][0],
                              learned.input_formats[0][1])
                    aot["informats"] = (pf, sf)
                    if chain == 1:
                        aot["jit"] = jax.jit(
                            step, donate_argnums=(0, 1),
                            in_shardings=(pf, sf, None, None, None)
                            + nextra,
                            out_shardings=(None, pf, sf, None))
                    else:
                        aot["jit"] = learned
                # relayout to the learned formats; only needed until the
                # caller threads returned (already-relaid) arrays back
                # in — re-issuing device_put on matching arrays is
                # avoided entirely after the first call
                if not aot.get("relaid"):
                    pf, sf = aot["informats"]
                    params = jax.device_put(params, pf)
                    states = jax.device_put(states, sf)
                    aot["relaid"] = True
                outs, new_params, new_states, aux_up = aot["jit"](
                    params, states, aux_values, rng, dv, *extra)
            else:
                if _progcache.enabled() and "exec" not in aot:
                    # Persistent program cache for the fused step: key by
                    # the LOWERED text — update_fn is arbitrary Python, so
                    # only lowering captures the actual program (a metadata
                    # key could collide across optimizer rules). Donation
                    # is part of the key and survives serialization. Any
                    # failure pins the plain-jit path for this step fn.
                    try:
                        lowered = jitted.lower(params, states, aux_values,
                                               rng, dv, *extra)
                        key = _progcache.lowered_key(
                            lowered.as_text(), donate=(0, 1),
                            extra="train_step")
                        exe = _progcache.load(key, kind="train_step")
                        if exe is None:
                            exe = lowered.compile()
                            _witness.record_compile("train_step",
                                                    key=key[:16])
                            _progcache.store(key, exe, note="train_step",
                                             kind="train_step")
                        aot["exec"] = exe
                    except Exception:
                        logging.getLogger("mxnet_tpu").warning(
                            "progcache: train-step AOT path failed; "
                            "using plain jit", exc_info=True)
                        aot["exec"] = None
                if aot.get("exec") is not None:
                    try:
                        outs, new_params, new_states, aux_up = aot["exec"](
                            params, states, aux_values, rng, dv, *extra)
                    except Exception:
                        # a stale/incompatible loaded executable must never
                        # fail the step: recompile via the jit path (inputs
                        # are intact — argument processing precedes any
                        # donation) and stop using the cached program
                        logging.getLogger("mxnet_tpu").warning(
                            "progcache: cached train step unusable; "
                            "recompiling", exc_info=True)
                        aot["exec"] = None
                        outs, new_params, new_states, aux_up = jitted(
                            params, states, aux_values, rng, dv, *extra)
                else:
                    outs, new_params, new_states, aux_up = jitted(
                        params, states, aux_values, rng, dv, *extra)
            for n, v in aux_up.items():
                self.aux_dict[n]._data = v
            self.outputs = [NDArray(o) for o in outs]
            return outs, new_params, new_states

        def run(params, states, data_values, *extra):
            # jit dispatch is async: the span measures the HOST side of the
            # step (argument prep, dispatch, first-call trace+compile); the
            # device timeline comes from the jax trace merged at dump time
            with _telemetry.span("executor.train_step", domain="executor",
                                 chain=chain, sharded=bool(sharded),
                                 stage=stage):
                if stage >= 3:
                    # marks the dispatch window in which the device runs
                    # the on-demand weight gathers (one-leaf prefetch under
                    # XLA's latency-hiding scheduler) — dump_profile()
                    # shows this span over the device timeline
                    with _telemetry.span("train.allgather_prefetch",
                                         domain="executor",
                                         gather_bytes=aot.get(
                                             "gather_bytes", 0)):
                        return _run_impl(params, states, data_values,
                                         *extra)
                return _run_impl(params, states, data_values, *extra)

        # trace-and-fuse metadata (engine.FuseOp): the pure `step` plus the
        # facts a consumer needs to stage it into a fused CapturedSequence.
        # AUTO-layout keeps its own compiled artifacts (learned formats)
        # that a re-trace inside a fused program would not reproduce, so
        # it is fuse-ineligible. The ZeRO paths (stages 1-3) fuse: the
        # carry is committed-sharded and FusedSequence keys the staged
        # program on the placement ("sharded"/"stage" stay here for
        # observers, not as a bail condition).
        run.fuse = {"step": step, "data_names": data_names,
                    "executor": self, "use_auto": use_auto,
                    "sharded": bool(sharded), "stage": stage}
        return run

    def _next_rng(self):
        if self._graph_needs_rng is None:
            self._graph_needs_rng = any(
                (not n.is_var) and n.op.needs_rng
                for n in self._symbol._nodes())
        if not self._graph_needs_rng and self._monitor_cb is None:
            # no stochastic op consumes the key: reuse one key instead of
            # paying jax.random.split's eager host cost (~2 ms) on EVERY
            # forward/step — the dominant Python overhead of the fused
            # fit step for deterministic graphs (docs/perf.md fit row).
            # With a monitor installed the key must stay per-step fresh:
            # _monitor_should_run dedupes fwd/bwd taps of one step by
            # comparing key bytes, and a constant key would silence every
            # tap after the first.
            if self._last_rng is None:
                self._last_rng = _random.next_key()
            return self._last_rng
        self._last_rng = _random.next_key()
        return self._last_rng

    # --- public API (reference Executor::Forward/Backward) ----------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data if isinstance(v, NDArray) else jnp.asarray(v)
        arg_values = {n: a._data for n, a in self.arg_dict.items()}
        aux_values = {n: a._data for n, a in self.aux_dict.items()}
        rng = self._next_rng()
        if self._monitor_should_run(rng):
            self._run_monitored(arg_values, aux_values, is_train, rng)
        fn = self._get_fwd(bool(is_train))
        with _telemetry.span("executor.forward", domain="executor",
                             is_train=bool(is_train)):
            outs, aux_up = fn(arg_values, aux_values, rng)
        if is_train:
            for n, v in aux_up.items():
                self.aux_dict[n]._data = v
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Runs the fused forward+backward computation (the separate-call API
        is preserved; the fused path keeps a single XLA executable — forward
        activations are recomputed inside, XLA CSEs what it can)."""
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        fn = self._get_fwd_bwd()
        arg_values = {n: a._data for n, a in self.arg_dict.items()}
        aux_values = {n: a._data for n, a in self.aux_dict.items()}
        rng = self._last_rng if self._last_rng is not None else self._next_rng()
        if self._monitor_should_run(rng):
            # tap every intermediate output for Monitor, exactly as the
            # reference taps during the training forward
            # (graph_executor.cc:761-781)
            self._run_monitored(arg_values, aux_values, True, rng)
        heads = None if out_grads is None else [g._data for g in out_grads]
        old = {n: self.grad_dict[n]._data for n in self._grad_names_list()
               if self.grad_req[n] == "add"}
        with _telemetry.span("executor.backward", domain="executor"):
            outs, aux_up, new_grads = fn(arg_values, aux_values, rng,
                                         heads, old)
        for n, g in zip(self._grad_names_list(), new_grads):
            self.grad_dict[n]._data = g
        for n, v in aux_up.items():
            self.aux_dict[n]._data = v
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def forward_backward(self, out_grads=None, **kwargs):
        """One fused train step: forward + backward in a single jitted call."""
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data if isinstance(v, NDArray) else jnp.asarray(v)
        self._next_rng()
        return self.backward(out_grads)

    def _grad_names_list(self):
        self._get_fwd_bwd()
        return self._grad_names

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for n, v in (arg_params or {}).items():
            if n in self.arg_dict:
                self.arg_dict[n]._data = jnp.asarray(v.asnumpy() if isinstance(v, NDArray) else v, self.arg_dict[n]._data.dtype)
            elif not allow_extra_params:
                raise MXNetError("unknown argument %s" % n)
        for n, v in (aux_params or {}).items():
            if n in self.aux_dict:
                self.aux_dict[n]._data = jnp.asarray(v.asnumpy() if isinstance(v, NDArray) else v, self.aux_dict[n]._data.dtype)
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %s" % n)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (compile cache keyed by shape ⇒ cheap)."""
        from . import ndarray as nd

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for n, shp in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(shp):
                new_args[n] = cur
            else:
                new_args[n] = nd.zeros(shp, dtype=str(cur._data.dtype))
        new_aux = {}
        for n, shp in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[n]
            new_aux[n] = cur if tuple(cur.shape) == tuple(shp) else nd.zeros(shp, dtype=str(cur._data.dtype))
        grads = None
        if self.grad_dict:
            grads = {n: nd.zeros(a.shape, dtype=str(a._data.dtype)) for n, a in new_args.items() if n in self.grad_dict}
        return Executor(self._symbol, self._ctx, new_args, grads, self.grad_req,
                        new_aux, compute_dtype=self._compute_dtype)

    # --- monitor (reference graph_executor.cc:761-781 monitor callback) ---
    def _monitor_should_run(self, rng):
        """Tap once per step: skip when the callback reports itself idle
        (Monitor between intervals) and dedupe forward+backward of the
        same step (same rng key)."""
        cb = self._monitor_cb
        if cb is None:
            return False
        is_active = getattr(cb, "is_active", None)
        if is_active is not None and not is_active():
            return False
        key = None if rng is None else np.asarray(rng).tobytes()
        if key is not None and key == self._monitored_rng:
            return False
        self._monitored_rng = key
        return True

    def set_monitor_callback(self, callback):
        self._monitor_cb = callback

    def _run_monitored(self, arg_values, aux_values, is_train, rng):
        """Eager re-evaluation reporting every intermediate output to the
        monitor callback (Monitor support, python/mxnet/monitor.py)."""
        sym = self._symbol
        internals = sym.get_internals()
        eval_fn = internals.build_eval()
        outs, _ = eval_fn(arg_values, aux_values, is_train, rng)
        for name, val in zip(internals.list_outputs(), outs):
            self._monitor_cb(name, NDArray(val))

    def print_summary(self):
        return self._symbol.debug_str()


class CapturedTrainStep:
    """Engine capture/replay harness for a steady-state train step
    (MXNET_ENGINE_CAPTURE; see engine.CapturedSequence).

    Each step is two engine ops — ``fit.load_data`` writes the executor's
    data buffers (mutable ``data_var``) and ``fit.step`` reads them and
    advances the donated params/states (const ``data_var``, mutable
    ``step_var``). The WAR edge data_var gives the replayed graph makes
    step N's read precede load N+1's write, so consecutive fit_steps
    pipeline safely through one submission per step after warmup.

    ``fence()`` is the happens-before edge readers of the fused state
    need (param writeback, metric update, output reads); callers must
    ``close()`` before dropping the harness so the engine vars retire.
    """

    def __init__(self, name: str = "train_step"):
        from . import engine
        self._engine = engine
        self.data_var: Optional[int] = engine.new_variable()
        self.step_var: Optional[int] = engine.new_variable()
        self.seq = engine.CapturedSequence(name=name)

    def step(self, load_fn, step_fn, fuse_load=None, fuse_step=None):
        """Run one iteration through the capture state machine: eager
        during warmup, one replayed submission once the sequence is
        stable. ``fuse_load``/``fuse_step`` carry the ops' traceable
        metadata (engine.FuseOp) so a stable sequence can lower into ONE
        fused XLA program under MXNET_ENGINE_FUSE; None keeps replay."""
        seq = self.seq
        seq.begin_step()
        seq.push(load_fn, mutable_vars=(self.data_var,),
                 name="fit.load_data", fuse=fuse_load)
        seq.push(step_fn, const_vars=(self.data_var,),
                 mutable_vars=(self.step_var,), name="fit.step",
                 fuse=fuse_step)
        seq.end_step()

    def invalidate(self, reason: str):
        self.seq.invalidate(reason)

    def fence(self):
        """Order every pushed/replayed step before the caller proceeds."""
        if self.data_var is not None:
            self._engine.fence([self.data_var, self.step_var],
                               name="fit.capture_fence").wait()

    def close(self):
        """Drain outstanding steps and retire the engine vars."""
        if self.data_var is None:
            return
        self.fence()
        self._engine.delete_variable(self.data_var)
        self._engine.delete_variable(self.step_var)
        self.data_var = self.step_var = None
