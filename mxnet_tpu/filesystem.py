"""Pluggable byte-stream openers for dataset URIs.

The reference reads .rec data through dmlc::Stream, whose URI schemes
(file://, s3://, hdfs://) are compile-time plugins (make/config.mk:132-144
USE_S3/USE_HDFS). The TPU-native equivalent is a runtime scheme registry:
``open_stream(uri, mode)`` dispatches on ``scheme://`` to a registered
opener returning a file-like object, so ``MXRecordIO`` (and everything
above it: ImageRecordIter, im2rec, checkpoints that go through it) can
read records from object storage without the framework knowing the
backend.

Built-ins:
- plain paths / ``file://`` — local filesystem
- ``memory://`` — an in-process byte store (tests, fixtures, ephemeral
  shards)
- any scheme fsspec knows (``gs://``, ``s3://``, ...) IF fsspec is
  importable — the runtime analogue of the reference's USE_S3 build flag;
  absent fsspec, those schemes raise with a clear message.

Register custom backends with ``register_stream_opener``.
"""
from __future__ import annotations

import io
import threading
from typing import Callable, Dict

from .base import MXNetError

_OPENERS: Dict[str, Callable] = {}
_MEMORY_FS: Dict[str, bytes] = {}
_MEMORY_LOCK = threading.Lock()


def register_stream_opener(scheme: str, opener: Callable):
    """opener(uri, mode) -> binary file-like. Registering an existing
    scheme replaces it (last wins, like dmlc registry overrides)."""
    _OPENERS[scheme] = opener


def split_scheme(uri: str):
    """('scheme', uri) — scheme '' for plain local paths. A Windows drive
    letter is not a scheme."""
    if "://" in uri:
        scheme = uri.split("://", 1)[0]
        if len(scheme) > 1:
            return scheme, uri
    return "", uri


def open_stream(uri: str, mode: str = "rb"):
    """Open ``uri`` for binary reading/writing via the scheme registry."""
    scheme, uri = split_scheme(uri)
    opener = _OPENERS.get(scheme)
    if opener is None:
        raise MXNetError(
            "no stream opener for scheme %r (uri %r); register one with "
            "mxnet_tpu.filesystem.register_stream_opener — remote schemes "
            "(gs/s3/...) need fsspec installed" % (scheme, uri))
    return opener(uri, mode)


def exists(uri: str) -> bool:
    """Existence probe across schemes (os.path.isfile for local)."""
    scheme, _ = split_scheme(uri)
    if scheme in ("", "file"):
        import os

        return os.path.isfile(uri[7:] if uri.startswith("file://") else uri)
    if scheme == "memory":
        with _MEMORY_LOCK:
            return uri in _MEMORY_FS
    try:
        with open_stream(uri, "rb"):
            return True
    except FileNotFoundError:
        return False
    # auth/network/permission errors propagate: "absent" and "unreachable"
    # must not be conflated (a transient blip would otherwise silently
    # load an indexed reader with an empty index)


# --- built-in openers -------------------------------------------------------

def _open_local(uri, mode):
    if uri.startswith("file://"):
        uri = uri[7:]
    return open(uri, mode)


class _MemoryWriter(io.BytesIO):
    """Commits its bytes to the in-process store on close."""

    def __init__(self, key):
        super().__init__()
        self._key = key

    def close(self):
        if not self.closed:
            with _MEMORY_LOCK:
                _MEMORY_FS[self._key] = self.getvalue()
        super().close()


def _open_memory(uri, mode):
    if "w" in mode:
        return _MemoryWriter(uri)
    with _MEMORY_LOCK:
        data = _MEMORY_FS.get(uri)
    if data is None:
        raise FileNotFoundError(uri)
    return io.BytesIO(data)


def memory_fs_clear():
    """Drop every memory:// object (test isolation)."""
    with _MEMORY_LOCK:
        _MEMORY_FS.clear()


def _open_fsspec(uri, mode):
    try:
        import fsspec
    except ImportError:
        raise MXNetError(
            "uri %r needs fsspec for its scheme (the runtime analogue of "
            "the reference's USE_S3/USE_HDFS build flags); pip install "
            "fsspec + the scheme's backend" % uri) from None
    try:
        return fsspec.open(uri, mode).open()
    except ImportError as e:  # fsspec present, scheme backend missing
        raise MXNetError(
            "uri %r: fsspec lacks this scheme's backend (%s)"
            % (uri, e)) from e


register_stream_opener("", _open_local)
register_stream_opener("file", _open_local)
register_stream_opener("memory", _open_memory)
for _scheme in ("gs", "s3", "hdfs", "http", "https", "az", "abfs"):
    register_stream_opener(_scheme, _open_fsspec)
