"""Central metrics registry — counters / gauges / histograms.

The surface keeps metric.py's ``get_name_value()`` convention (parallel
name/value lists zipped into pairs) and adds ``exposition()`` rendering
the Prometheus text format — the seam for a future HTTP front-end
(ROADMAP serving SLOs).

Counters are ON by default (``MXNET_TELEMETRY=0`` turns every mutation
into a branch-and-return); unlike spans they need no domain selection —
an ``inc()`` is one lock + add.

Locking discipline (mxnet_tpu.analysis lockorder): ``Registry._lock``
guards only the name→metric tables; renders and reads snapshot the
tables under the lock and evaluate metric values (including gauge
callbacks — user code) OUTSIDE it.
"""
from __future__ import annotations

import math
import re
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .tracer import _master_enabled

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: the Prometheus text exposition content type — the single constant the
#: HTTP front-end's ``/metrics`` response and any scraper agree on
#: (text format 0.0.4; docs/observability.md)
CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    return name if name and not name[0].isdigit() else "_" + name


class Counter:
    """Monotonic counter (``get_name_value()`` → one pair).

    Optional ``labels`` make this one SERIES of a labeled family — the
    registry keys labeled counters by ``name{k="v"}`` (same contract as
    labeled gauges; the unlabeled spelling is unchanged)."""

    __slots__ = ("name", "help", "_value", "_lock", "labels")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()
        self.labels = dict(labels) if labels else None

    def sample_name(self) -> str:
        return self.name + _render_labels(self.labels)

    def inc(self, n=1):
        if not _master_enabled():
            return
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def get_name_value(self):
        return [(self.sample_name(), self._value)]


def _escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline (the only three escapes the 0.0.4 grammar has)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(labels) -> str:
    """``{k="v",...}`` suffix, keys sorted (stable registry identity)."""
    if not labels:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (_sanitize(str(k)), _escape_label_value(v))
        for k, v in sorted(labels.items()))


class Gauge:
    """Point-in-time value: either ``set()`` explicitly or computed by a
    callback ``fn`` at read time (e.g. the engine's pending-op depth).

    Optional ``labels`` make this one SERIES of a labeled family — the
    registry keys labeled gauges by ``name{k="v"}``, so
    ``gauge("kv_bytes", labels={"dtype": "int8"})`` and the unlabeled
    ``gauge("kv_bytes")`` are distinct metrics (the unlabeled spelling is
    bitwise unchanged by this feature)."""

    __slots__ = ("name", "help", "_value", "_fn", "labels")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None,
                 help: str = "", labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self._value = 0.0
        self._fn = fn
        self.labels = dict(labels) if labels else None

    def sample_name(self) -> str:
        return self.name + _render_labels(self.labels)

    def set(self, v):
        if not _master_enabled():
            return
        self._value = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return float("nan")
        return self._value

    def get_name_value(self):
        return [(self.sample_name(), self.value)]


class Histogram:
    """Cumulative-bucket histogram (Prometheus ``le`` semantics).

    ``observe(v, exemplar="<trace_id>")`` attaches an OpenMetrics
    exemplar to the bucket the observation lands in (last-write-wins
    per bucket): the exposition then renders
    ``... # {trace_id="..."} <value> <unix_ts>`` after the bucket
    sample, which is how a Prometheus latency bucket links back to one
    concrete request timeline in the flight recorder."""

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_exemplars", "_lock")

    DEFAULT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                      250, 500, 1000)

    def __init__(self, name: str, buckets: Sequence[float] = (),
                 help: str = ""):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets)) or self.DEFAULT_BUCKETS
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._exemplars: List[Optional[tuple]] = \
            [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: Optional[str] = None):
        if not _master_enabled():
            return
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar:
                self._exemplars[i] = (str(exemplar), float(v), time.time())

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def exemplars(self) -> List[Optional[tuple]]:
        """Per-bucket ``(trace_id, value, unix_ts)`` or None (last
        slot is the +Inf bucket)."""
        with self._lock:
            return list(self._exemplars)

    def get_name_value(self):
        counts, s, n = self.snapshot()
        return [("%s_sum" % self.name, s), ("%s_count" % self.name, n)]


class Registry:
    """Process-wide metric registry (``telemetry.registry`` singleton).

    ``counter``/``gauge``/``histogram`` are get-or-create by name.
    ``register_group(prefix, obj)`` adopts an object exposing
    ``get_name_value()`` (the metric.py convention — e.g. a live
    ``ServingMetrics``) wholesale: the registry holds only a weakref, so
    short-lived servers don't leak, and each instance gets a stable
    ``sid`` label in the exposition.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._groups: List[Tuple[str, int, "weakref.ref"]] = []
        self._next_sid = 0

    def _get_or_create(self, name, cls, *args, key=None, **kwargs):
        key = key or name
        with self._lock:
            m = self._metrics.get(key)
        if m is None:
            # construct outside the lock (lockorder: no callable runs under
            # _lock); a racing creator loses benignly to setdefault
            fresh = cls(name, *args, **kwargs)
            with self._lock:
                m = self._metrics.setdefault(key, fresh)
        if not isinstance(m, cls):
            raise TypeError("metric %r already registered as %s"
                            % (name, type(m).__name__))
        return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        """Labeled counters are keyed by ``name{k="v"}`` — each label set
        is its own series (same contract as :meth:`gauge`)."""
        return self._get_or_create(name, Counter, help, labels,
                                   key=name + _render_labels(labels))

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        """Labeled gauges are keyed by ``name{k="v"}`` — each label set is
        its own series; omitting ``labels`` keeps the historical
        single-series behavior."""
        return self._get_or_create(name, Gauge, fn, help, labels,
                                   key=name + _render_labels(labels))

    def histogram(self, name: str, buckets: Sequence[float] = (),
                  help: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, buckets, help)

    def register_group(self, prefix: str, obj) -> int:
        """Adopt ``obj.get_name_value()`` under ``prefix`` (weakref'd);
        returns the instance's ``sid`` label value."""
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            self._groups.append((prefix, sid, weakref.ref(obj)))
            return sid

    # --- reads (no user code under _lock) --------------------------------
    def _snapshot(self):
        with self._lock:
            metrics = list(self._metrics.values())
            groups = list(self._groups)
        live = []
        dead = False
        for prefix, sid, ref in groups:
            obj = ref()
            if obj is None:
                dead = True
            else:
                live.append((prefix, sid, obj))
        if dead:  # prune collected groups so the table stays bounded
            with self._lock:
                self._groups = [g for g in self._groups if g[2]() is not None]
        return metrics, live

    def get(self):
        """(names, values) — metric.py's EvalMetric.get() shape, covering
        registry metrics and live groups (group entries as
        ``<prefix>_<name>``)."""
        metrics, groups = self._snapshot()
        names, values = [], []
        for m in metrics:
            for n, v in m.get_name_value():
                names.append(n)
                values.append(v)
        for prefix, _sid, obj in groups:
            for n, v in obj.get_name_value():
                names.append("%s_%s" % (prefix, _sanitize(str(n))))
                values.append(v)
        return names, values

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))

    def exposition(self) -> str:
        """Render the Prometheus text exposition format (0.0.4 — serve it
        with ``CONTENT_TYPE_LATEST``). EVERY family gets a ``# HELP`` and
        a ``# TYPE`` line, emitted once per family (labeled gauge series
        and group instances share theirs); families without declared help
        fall back to the family name, so scrapers always see well-formed
        framing. Gauge callbacks and group ``get_name_value()`` run
        outside the registry lock."""
        metrics, groups = self._snapshot()
        out: List[str] = []
        headed = set()  # families whose HELP/TYPE already went out

        def _head(name: str, kind: str, help_text: str):
            if name in headed:
                return
            headed.add(name)
            out.append("# HELP %s %s"
                       % (name, (help_text or name)
                          .replace("\\", "\\\\").replace("\n", "\\n")))
            out.append("# TYPE %s %s" % (name, kind))

        for m in metrics:
            name = _sanitize(m.name)
            if isinstance(m, Counter):
                _head(name, "counter", m.help)
                out.append("%s%s %s" % (name, _render_labels(m.labels),
                                        _fmt(m.value)))
            elif isinstance(m, Gauge):
                _head(name, "gauge", m.help)
                out.append("%s%s %s" % (name, _render_labels(m.labels),
                                        _fmt(m.value)))
            elif isinstance(m, Histogram):
                _head(name, "histogram", m.help)
                counts, s, n = m.snapshot()
                ex = m.exemplars()
                acc = 0
                for i, (b, c) in enumerate(zip(m.buckets, counts)):
                    acc += c
                    out.append('%s_bucket{le="%s"} %d%s'
                               % (name, _fmt(b), acc, _fmt_exemplar(ex[i])))
                out.append('%s_bucket{le="+Inf"} %d%s'
                           % (name, n, _fmt_exemplar(ex[-1])))
                out.append("%s_sum %s" % (name, _fmt(s)))
                out.append("%s_count %d" % (name, n))
        for prefix, sid, obj in groups:
            for n, v in obj.get_name_value():
                fam = "%s_%s" % (_sanitize(prefix), _sanitize(str(n)))
                _head(fam, "gauge", "")
                out.append('%s{sid="%d"} %s' % (fam, sid, _fmt(v)))
        return "\n".join(out) + "\n"

    def reset(self):
        """Drop every metric and group (tests)."""
        with self._lock:
            self._metrics.clear()
            self._groups.clear()


def _fmt_exemplar(ex) -> str:
    """OpenMetrics exemplar suffix — `` # {trace_id="..."} v ts`` —
    or the empty string. Exemplars exist only in the OpenMetrics
    grammar; they appear solely on ``_bucket`` lines of histograms
    that were observed WITH an exemplar, so every other family stays
    bitwise 0.0.4 (docs/observability.md, Request tracing)."""
    if not ex:
        return ""
    tid, v, ts = ex
    return ' # {trace_id="%s"} %s %s' % (
        _escape_label_value(tid), _fmt(v), repr(float(ts)))


def _fmt(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: the process-wide registry (``telemetry.registry``)
registry = Registry()
