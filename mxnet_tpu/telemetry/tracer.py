"""Lock-light host-side trace recorder.

The reference profiler (src/engine/profiler.cc, SURVEY §5.1) stamps
per-op begin/end inside engine workers into per-thread `ProfileStat`
blocks and merges them at dump time. Same design here:

- every thread appends events to its OWN ring buffer (a bounded
  ``collections.deque`` — appends are GIL-atomic, no lock on the hot
  path); buffers register themselves in a global list once, under a
  lock, at first use;
- ``drain_events()``/``chrome_events()`` walk all buffers at dump time
  (the only cross-thread read, done with ``popleft`` so concurrent
  appends are never lost);
- the disabled path is a branch-and-return: ``span()`` returns a no-op
  singleton unless the event's *domain* was enabled.

Domains (``engine``, ``serving``, ``kvstore``, ``executor``,
``monitor``, ...) are selected via ``MXNET_PROFILER=engine,serving``
(or ``1``/``all``); spans are OFF by default. ``MXNET_TELEMETRY=0`` is
the master kill for the whole subsystem (docs/observability.md,
docs/env_var.md).

Timestamps use ``time.monotonic_ns()`` — the same clock family as the
serving deadlines (``time.monotonic``), so request queue time can be
reconstructed exactly with ``complete()``.

Instrumentation calls must stay OUTSIDE jitted/shard_mapped code: a
traced function runs once at trace time, so a span inside it measures
tracing, not execution. ``mxnet_tpu.analysis.trace_purity`` enforces
this (rule ``telemetry-in-jit``).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: per-thread ring size default (events beyond it age out oldest-first).
#: MXNET_TELEMETRY_BUFFER is re-read at every ring CREATION — a test or
#: forked worker can resize without reimporting; threads whose rings
#: already exist keep their size.
_BUFFER_SIZE = int(os.environ.get("MXNET_TELEMETRY_BUFFER", "65536"))


def _buffer_size() -> int:
    try:
        return int(os.environ.get("MXNET_TELEMETRY_BUFFER") or _BUFFER_SIZE)
    except ValueError:
        return _BUFFER_SIZE

clock_ns = time.monotonic_ns


def _master_enabled() -> bool:
    return os.environ.get("MXNET_TELEMETRY", "1") != "0"


# --- per-thread buffers ------------------------------------------------------
class _ThreadBuffer:
    __slots__ = ("events", "tid", "name")

    def __init__(self):
        self.events: deque = deque(maxlen=_buffer_size())
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.name = t.name


_local = threading.local()
_buffers: List[_ThreadBuffer] = []
_buffers_lock = threading.Lock()


def _buf() -> _ThreadBuffer:
    b = getattr(_local, "buf", None)
    if b is None:
        b = _ThreadBuffer()
        _local.buf = b
        with _buffers_lock:
            _buffers.append(b)
    return b


# --- domain gating -----------------------------------------------------------
_spans_on = False
_all_domains = False
_domains: frozenset = frozenset()


def enable_spans(domains: str = "all"):
    """Turn span recording on for a comma-separated domain list (``"all"``
    or ``"1"`` enables every domain). No-op under ``MXNET_TELEMETRY=0``."""
    global _spans_on, _all_domains, _domains
    if not _master_enabled():
        return
    toks = [t for t in str(domains).replace(" ", "").split(",") if t]
    _all_domains = any(t in ("all", "1", "*") for t in toks)
    _domains = frozenset(toks)
    _spans_on = bool(toks)


def disable_spans():
    global _spans_on, _all_domains, _domains
    _spans_on = False
    _all_domains = False
    _domains = frozenset()


def enabled(domain: str) -> bool:
    """Fast probe: is span recording on for this domain? Call sites use it
    to skip building span arguments entirely on the disabled path."""
    return _spans_on and (_all_domains or domain in _domains)


def enabled_domains() -> str:
    return "all" if _all_domains else ",".join(sorted(_domains))


# env default: MXNET_PROFILER=engine,serving (spans stay off when unset)
_env_profiler = os.environ.get("MXNET_PROFILER", "")
if _env_profiler and _env_profiler not in ("0", "off", "none"):
    enable_spans(_env_profiler)
del _env_profiler


# --- span sink (flight recorder tee) -----------------------------------------
_span_sink = None


def set_span_sink(fn):
    """Install ``fn(ph, name, domain, ts_ns, dur_ns, args)``, invoked on
    the recording thread for every completed event whose args carry
    ``trace_id``/``trace_ids`` stamps — the flight recorder's feed
    (telemetry.flight installs itself at import). Only runs when spans
    are ON: the disabled path never reaches it. Returns the prior sink."""
    global _span_sink
    prev = _span_sink
    _span_sink = fn
    return prev


def _tee(ph, name, domain, ts_ns, dur_ns, args):
    s = _span_sink
    if (s is None or args is None
            or ("trace_id" not in args and "trace_ids" not in args)):
        return
    try:
        s(ph, name, domain, ts_ns, dur_ns, args)
    except Exception:
        pass  # a broken sink must never take down the traced code


# --- event recording ---------------------------------------------------------
# raw event: (ph, name, domain, ts_ns, dur_ns, args_or_None)
class _Span:
    """Context manager recording one complete ("X") event."""

    __slots__ = ("name", "domain", "args", "t0")

    def __init__(self, name, domain, args):
        self.name = name
        self.domain = domain
        self.args = args or None

    def __enter__(self):
        self.t0 = clock_ns()
        return self

    def annotate(self, **args):
        """Attach/overwrite args discovered while the span is open."""
        self.args = dict(self.args or (), **args)
        return self

    def __exit__(self, *exc):
        t1 = clock_ns()
        _buf().events.append(
            ("X", self.name, self.domain, self.t0, t1 - self.t0, self.args))
        _tee("X", self.name, self.domain, self.t0, t1 - self.t0, self.args)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def annotate(self, **args):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, domain: str = "app", **args):
    """``with telemetry.span("engine.op", domain="engine", vars=3): ...``
    — records an "X" event on the calling thread's ring buffer. Returns a
    shared no-op object when the domain is disabled (branch-and-return;
    nothing is allocated)."""
    if not (_spans_on and (_all_domains or domain in _domains)):
        return _NOOP
    return _Span(name, domain, args)


def begin(name: str, domain: str = "app", **args) -> Optional[tuple]:
    """Start an async span; returns an opaque token (or None when the
    domain is disabled). Pass the token to :func:`end` from ANY thread —
    the completed event lands on the *beginning* thread's buffer, so one
    logical op stays on one trace row even when its ``on_complete`` fires
    elsewhere (the engine push_async shape)."""
    if not (_spans_on and (_all_domains or domain in _domains)):
        return None
    return (_buf(), name, domain, clock_ns(), args or None)


def end(token: Optional[tuple], **extra_args):
    """Finish an async span started with :func:`begin` (None-safe)."""
    if token is None:
        return
    buf, name, domain, t0, args = token
    if extra_args:
        args = dict(args or (), **extra_args)
    end_tid = threading.get_ident()
    if end_tid != buf.tid:
        args = dict(args or (), end_tid=end_tid)
    dur = clock_ns() - t0
    buf.events.append(("X", name, domain, t0, dur, args))
    _tee("X", name, domain, t0, dur, args)


def complete(name: str, domain: str = "app", start_ns: int = 0,
             end_ns: Optional[int] = None, **args):
    """Record an "X" event with EXPLICIT ``monotonic_ns`` timestamps —
    for lifecycle stages whose start was stamped elsewhere (e.g. serving
    queue time measured from ``Request.submitted``)."""
    if not (_spans_on and (_all_domains or domain in _domains)):
        return
    t1 = clock_ns() if end_ns is None else end_ns
    a = args or None
    _buf().events.append(
        ("X", name, domain, start_ns, max(0, t1 - start_ns), a))
    _tee("X", name, domain, start_ns, max(0, t1 - start_ns), a)


def instant(name: str, domain: str = "app", **args):
    """Record an instant ("i") event — a point-in-time marker."""
    if not (_spans_on and (_all_domains or domain in _domains)):
        return
    t = clock_ns()
    a = args or None
    _buf().events.append(("i", name, domain, t, 0, a))
    _tee("i", name, domain, t, 0, a)


def mark_begin(name: str, domain: str = "app", **args):
    """Emit a duration-begin ("B") event; pair with :func:`mark_end` ON
    THE SAME THREAD (chrome matches B/E per tid). Used for user-delimited
    windows like the profiler run/stop bracket."""
    if not (_spans_on and (_all_domains or domain in _domains)):
        return
    _buf().events.append(("B", name, domain, clock_ns(), 0, args or None))


def mark_end(name: str, domain: str = "app", **args):
    if not (_spans_on and (_all_domains or domain in _domains)):
        return
    _buf().events.append(("E", name, domain, clock_ns(), 0, args or None))


# --- drain / dump ------------------------------------------------------------
def drain_events(clear: bool = True) -> List[tuple]:
    """Collect raw events from every thread buffer as
    ``(ph, name, domain, ts_ns, dur_ns, args, tid, thread_name)`` tuples.
    ``clear=True`` (the default) empties the buffers with ``popleft`` so
    events appended concurrently are kept for the next drain, never lost."""
    with _buffers_lock:
        bufs = list(_buffers)
    out: List[tuple] = []
    for b in bufs:
        if clear:
            evs = []
            dq = b.events
            while True:
                try:
                    evs.append(dq.popleft())
                except IndexError:
                    break
        else:
            evs = list(b.events)
        for ev in evs:
            out.append(ev + (b.tid, b.name))
    return out


def chrome_events(clear: bool = True) -> List[dict]:
    """Drain to chrome://tracing ``traceEvents`` dicts (``ph`` "X"/"B"/
    "E"/"i", pid/tid, ts/dur in µs), preceded by ``thread_name`` metadata
    events, sorted so ts is monotonic per tid."""
    pid = os.getpid()
    raw = drain_events(clear=clear)
    seen_tids: Dict[int, str] = {}
    evs: List[dict] = []
    for ph, name, domain, ts_ns, dur_ns, args, tid, tname in raw:
        seen_tids.setdefault(tid, tname)
        e = {"name": name, "cat": domain, "ph": ph, "pid": pid, "tid": tid,
             "ts": ts_ns / 1000.0}
        if ph == "X":
            e["dur"] = dur_ns / 1000.0
        elif ph == "i":
            e["s"] = "t"
        if args:
            e["args"] = dict(args)
        evs.append(e)
    evs.sort(key=lambda e: (e["tid"], e["ts"]))
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": tname}} for tid, tname in seen_tids.items()]
    return meta + evs


def dump_ring(dir: Optional[str] = None) -> Optional[str]:
    """Drain this process's ring buffers to a pid-tagged file
    ``<dir>/telemetry_ring_<pid>.json`` (a chrome ``traceEvents`` list;
    pid rides every event). Worker processes — dist kvstore servers,
    dryrun subprocesses — call this at exit (or automatically when
    ``MXNET_TELEMETRY_RING_DIR`` is set), and ``profiler.dump_profile()``
    merges every ring file it finds into the single trace. Returns the
    path, or None when no directory is configured."""
    import json

    d = dir or os.environ.get("MXNET_TELEMETRY_RING_DIR")
    if not d:
        return None
    evs = chrome_events(clear=True)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "telemetry_ring_%d.json" % os.getpid())
    tmp = "%s.%d.tmp" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(evs, f)
    os.replace(tmp, path)
    return path


def reset():
    """Drop every buffered event (buffers stay registered)."""
    drain_events(clear=True)
