"""Propagated trace context — W3C ``traceparent`` in, span trees out.

PR 17 made the *request* the unit of accountability, but spans were
per-thread events with no request identity: nothing could answer "why
was request X slow" once its work hopped from the HTTP handler thread
to the batch former to an engine worker. This module carries that
identity:

- ``parse_traceparent()`` / ``to_traceparent()`` speak the W3C Trace
  Context wire format (``00-<32 hex trace>-<16 hex span>-<2 hex
  flags>``) so an upstream proxy's ids are honored at the HTTP edge;
- ``mint()`` creates a fresh context when the caller sent none, and
  ``child()`` derives a per-stage context (new span_id, parent set to
  the creating span) so a request's events assemble into ONE tree;
- ``use()`` / ``current_context()`` is the thread-local carry. Serving
  stores the context ON the ``Request``/``TokenStream`` object and
  re-installs it inside engine ops, so the context survives the
  thread hops that ``threading.local`` alone cannot;
- ``mint_request_id()`` is the one request-id mint (moved here from
  the HTTP front-end so server-side submits and the PS plane share
  the same id space).

Cost discipline (the < 3% spans-off gate): a context is three short
strings; nothing here allocates per-*span* — only per-request — and
``current_context()`` on a thread with no context is a single
``getattr`` returning None.

Span-id minting is a process-salted counter, not ``os.urandom`` per
span: unique across the fleet's processes (64-bit random salt) and
~30x cheaper than a syscall per id.
"""
from __future__ import annotations

import itertools
import os
import threading
import uuid
from typing import Optional

_TRACEPARENT_HEADER = "traceparent"

# process-salted span-id mint: high 40 bits random (per-process), low
# bits a counter — collision-free within a process, fleet-unique with
# overwhelming probability across processes
_ids = itertools.count(int.from_bytes(os.urandom(5), "big") << 24)
_MASK64 = (1 << 64) - 1


def mint_span_id() -> str:
    return "%016x" % (next(_ids) & _MASK64)


def mint_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars, the W3C trace-id width


def mint_request_id() -> str:
    """The one request-id mint (previously inlined in the HTTP
    front-end): 16 hex chars, stable enough to grep a fleet's logs."""
    return uuid.uuid4().hex[:16]


class TraceContext:
    """One hop of a distributed trace: ``trace_id`` names the request's
    whole tree, ``span_id`` this hop, ``parent_id`` the hop that spawned
    it (None at the root). ``request_id`` rides along so operator-facing
    surfaces (error bodies, flight bundles) can key by either id."""

    __slots__ = ("trace_id", "span_id", "parent_id", "request_id",
                 "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None,
                 request_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.request_id = request_id
        self.sampled = sampled

    def child(self) -> "TraceContext":
        """New span under this one (same trace, fresh span_id)."""
        return TraceContext(self.trace_id, mint_span_id(), self.span_id,
                            self.request_id, self.sampled)

    def stamps(self) -> dict:
        """The span-args dict every instrumented call site attaches —
        the keys the flight recorder and tree assembly key on."""
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.request_id:
            d["request_id"] = self.request_id
        return d

    def __repr__(self):
        return ("TraceContext(trace_id=%r, span_id=%r, parent_id=%r)"
                % (self.trace_id, self.span_id, self.parent_id))


def mint(request_id: Optional[str] = None) -> TraceContext:
    """Fresh root context (no inbound ``traceparent``)."""
    return TraceContext(mint_trace_id(), mint_span_id(),
                        request_id=request_id or mint_request_id())


def parse_traceparent(header: Optional[str],
                      request_id: Optional[str] = None
                      ) -> Optional[TraceContext]:
    """Parse a W3C ``traceparent`` header value. Returns None on any
    malformation (the spec says a broken header is *ignored*, not an
    error — the edge then mints a fresh context). The caller's span id
    becomes ``parent_id``; a fresh ``span_id`` is minted for our side."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, parent_span, flags = parts
    if (len(version) != 2 or len(trace_id) != 32 or len(parent_span) != 16
            or len(flags) != 2):
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(parent_span, 16)
        fl = int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or trace_id == "0" * 32 or parent_span == "0" * 16:
        return None
    return TraceContext(trace_id, mint_span_id(), parent_span,
                        request_id=request_id or mint_request_id(),
                        sampled=bool(fl & 0x01))


def to_traceparent(ctx: TraceContext) -> str:
    """Serialize for the wire (HTTP response echo, PS plane headers)."""
    return "00-%s-%s-%s" % (ctx.trace_id, ctx.span_id,
                            "01" if ctx.sampled else "00")


def from_headers(headers, request_id: Optional[str] = None) -> TraceContext:
    """HTTP-edge entry: honor an inbound ``traceparent`` (and
    ``x-request-id``) or mint fresh ids. ``headers`` is any mapping with
    ``.get`` (http.client's message object qualifies)."""
    rid = request_id or headers.get("x-request-id") or mint_request_id()
    ctx = parse_traceparent(headers.get(_TRACEPARENT_HEADER), rid)
    return ctx if ctx is not None else mint(rid)


# --- thread-local carry ------------------------------------------------------
_tls = threading.local()


def current_context() -> Optional[TraceContext]:
    """The context installed on this thread, or None. This is the
    spans-off fast path for every propagation site: one getattr, no
    allocation. MUST NOT be read inside jitted code (it runs once at
    trace time — ``mxnet_tpu.analysis`` rule ``telemetry-in-jit``)."""
    return getattr(_tls, "ctx", None)


class use:
    """``with context.use(ctx): ...`` installs ``ctx`` as the thread's
    current context for the block (None is allowed and means "clear").
    Re-entrant: the previous context is restored on exit — engine ops
    re-installing a request's context nest under the worker's own."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def set_current(ctx: Optional[TraceContext]):
    _tls.ctx = ctx


def clear_current():
    _tls.ctx = None
