"""SLO flight recorder — a bounded ring of recent request timelines
plus anomaly-triggered diagnostic bundles.

The compile witness (PR 18) counts anomalies; this module *snapshots*
them, the way a flight data recorder keeps the last N minutes so the
interesting window is already on disk when something goes wrong. Two
stores:

- a **live** table of per-trace spans, fed by a tracer tee: every
  span whose args carry ``trace_id`` (stamped by
  ``context.TraceContext.stamps()``) is copied here as it completes,
  from whatever thread recorded it. Batch-level spans
  (``serving.dispatch`` / ``decode.step``) carry ``trace_ids`` — a
  list — and fan out to every member trace, so a request's tree
  includes the batches it rode;
- a **ring** of completed request timelines (``MXNET_FLIGHT_RING``,
  default 256): when serving reports a request finished
  (``request_end``), its live spans move into one immutable record.

Anomaly triggers — deadline miss, shed, ``compiles_after_steady``
increment, drain start, and the ``MXNET_SLOW_REQUEST_MS`` threshold —
call :func:`on_anomaly`, which writes a diagnostic bundle (victim
span tree + recent ring + full metrics exposition + MXNET_* config)
to ``MXNET_FLIGHT_DIR`` and bumps ``flight_bundles_total{trigger=}``.
Bundle files are pid-tagged; at most ``MXNET_FLIGHT_MAX_BUNDLES``
(default 16) are written per process — beyond that the trigger still
counts (``flight_bundles_dropped_total``) but disk stays bounded.

The recorder is ON by default (``MXNET_FLIGHT_RECORDER=0`` disables;
``MXNET_TELEMETRY=0`` kills it with the rest of telemetry). With
spans off it still records request completions and triggers — the
ring then holds ids/latency/outcome without span trees. Per-span cost
exists only when spans are on AND the span was trace-stamped.

Locking: one leaf lock (rank 100 — never taken while holding any
serving/engine lock, and no user code runs under it). Metric bumps
and file writes happen OUTSIDE the lock.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from . import tracer
from .metrics import registry
from .context import TraceContext

#: per-trace span cap — a runaway stream cannot grow one timeline
#: unbounded (oldest kept: the edge/root spans matter most)
_MAX_SPANS_PER_TRACE = 256
#: live-table trace cap (LRU eviction) — traces that never report
#: completion (crashed client, lost stream) age out
_MAX_LIVE_TRACES = 1024

_enabled = (os.environ.get("MXNET_FLIGHT_RECORDER", "1") != "0"
            and os.environ.get("MXNET_TELEMETRY", "1") != "0")

_lock = threading.Lock()
_live: "OrderedDict[str, List[dict]]" = OrderedDict()
_ring: deque = deque(
    maxlen=max(1, int(os.environ.get("MXNET_FLIGHT_RING", "256"))))
_bundles_written: List[str] = []
_triggers: deque = deque(maxlen=64)
_seq = 0


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> bool:
    """Flip the recorder (tests / embedders); returns the prior state."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def _dir() -> str:
    return os.environ.get("MXNET_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "mxnet_tpu_flight")


def _slow_ms() -> float:
    try:
        return float(os.environ.get("MXNET_SLOW_REQUEST_MS", "0") or 0)
    except ValueError:
        return 0.0


def _max_bundles() -> int:
    return int(os.environ.get("MXNET_FLIGHT_MAX_BUNDLES", "16"))


# --- tracer tee --------------------------------------------------------------
def _sink(ph: str, name: str, domain: str, ts_ns: int, dur_ns: int,
          args: Optional[dict]):
    """Installed as the tracer's span sink: called (from the recording
    thread) for every completed span whose args are trace-stamped."""
    if not _enabled or not args:
        return
    span = {"ph": ph, "name": name, "domain": domain, "ts_ns": ts_ns,
            "dur_ns": dur_ns, "args": dict(args),
            "tid": threading.get_ident()}
    tids = args.get("trace_ids")
    one = args.get("trace_id")
    targets = list(tids) if tids else []
    if one:
        targets.append(one)
    with _lock:
        for t in targets:
            lst = _live.get(t)
            if lst is None:
                while len(_live) >= _MAX_LIVE_TRACES:
                    _live.popitem(last=False)
                lst = _live[t] = []
            if len(lst) < _MAX_SPANS_PER_TRACE:
                lst.append(span)


tracer.set_span_sink(_sink)


# --- request lifecycle -------------------------------------------------------
def request_end(trace: Optional[TraceContext], ok: bool,
                code: Optional[str] = None,
                latency_ms: Optional[float] = None,
                kind: str = "predict", request_id: Optional[str] = None):
    """Serving reports one request finished (success OR failure). Moves
    the trace's live spans into the completed ring and fires the
    slow-request trigger when the ``MXNET_SLOW_REQUEST_MS`` threshold
    is set and exceeded. Spans-off cost: one lock + deque append."""
    if not _enabled:
        return
    tid = trace.trace_id if trace is not None else None
    rid = request_id or (trace.request_id if trace is not None else None)
    rec = {"request_id": rid, "trace_id": tid, "ok": bool(ok),
           "code": code, "latency_ms": latency_ms, "kind": kind,
           "ts": time.time()}
    with _lock:
        rec["spans"] = _live.pop(tid, []) if tid else []
        _ring.append(rec)
    slow = _slow_ms()
    if ok and slow > 0 and latency_ms is not None and latency_ms > slow:
        on_anomaly("slow_request", trace, request_id=rid,
                   latency_ms=latency_ms, threshold_ms=slow)


# --- anomaly triggers --------------------------------------------------------
def on_anomaly(trigger: str, trace: Optional[TraceContext] = None,
               **detail) -> Optional[str]:
    """An SLO anomaly happened: write one diagnostic bundle to
    ``MXNET_FLIGHT_DIR`` (span tree of the victim trace if known, the
    completed-request ring, the full metrics exposition, and MXNET_*
    config) and bump ``flight_bundles_total{trigger=...}``. Returns the
    bundle path, or None when disabled / over the per-process cap."""
    global _seq
    if not _enabled:
        return None
    tid = trace.trace_id if trace is not None else None
    with _lock:
        _triggers.append({"trigger": trigger, "trace_id": tid,
                          "ts": time.time(), "detail": dict(detail)})
        if len(_bundles_written) >= _max_bundles():
            capped = True
            path = None
        else:
            capped = False
            _seq += 1
            path = os.path.join(_dir(), "flight_%s_%d_%04d.json"
                                % (trigger, os.getpid(), _seq))
            _bundles_written.append(path)
        victim = list(_live.get(tid, ())) if tid else []
        ring = [dict(r) for r in _ring]
    if capped:
        registry.counter(
            "flight_bundles_dropped_total",
            "flight bundles skipped past MXNET_FLIGHT_MAX_BUNDLES").inc()
        return None
    bundle = {
        "trigger": trigger,
        "ts": time.time(),
        "pid": os.getpid(),
        "trace_id": tid,
        "request_id": (detail.get("request_id")
                       or (trace.request_id if trace is not None else None)),
        "detail": detail,
        "victim": _assemble(tid, victim, _ring_entry(ring, tid)),
        "recent_requests": ring,
        "metrics": registry.exposition(),
        "config": {k: v for k, v in os.environ.items()
                   if k.startswith("MXNET_")},
    }
    try:
        os.makedirs(_dir(), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        with _lock:
            if path in _bundles_written:
                _bundles_written.remove(path)
        return None
    registry.counter("flight_bundles_total",
                     "diagnostic bundles written by the flight recorder",
                     labels={"trigger": trigger}).inc()
    return path


def _ring_entry(ring: List[dict], trace_id: Optional[str]):
    if not trace_id:
        return None
    for r in reversed(ring):
        if r.get("trace_id") == trace_id:
            return r
    return None


# --- span-tree assembly ------------------------------------------------------
def _assemble(trace_id: Optional[str], spans: List[dict],
              completed: Optional[dict] = None) -> Optional[dict]:
    """Nest a flat span list into one tree via span_id/parent_id. Spans
    whose parent is unknown (root, or a batch span fanned in from
    another request's dispatch) become top-level children, ordered by
    start time — the tree is total even with a lossy ring."""
    if completed and not spans:
        spans = completed.get("spans", [])
    if trace_id is None and not spans:
        return None
    nodes: Dict[str, dict] = {}
    order: List[dict] = []
    for s in sorted(spans, key=lambda s: s.get("ts_ns", 0)):
        a = s.get("args") or {}
        node = dict(s)
        node["children"] = []
        sid = a.get("span_id")
        if sid:
            nodes.setdefault(sid, node)
        order.append(node)
    roots: List[dict] = []
    for node in order:
        a = node.get("args") or {}
        parent = nodes.get(a.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    out = {"trace_id": trace_id, "spans": roots,
           "n_spans": len(order)}
    if completed:
        for k in ("request_id", "ok", "code", "latency_ms", "kind"):
            out[k] = completed.get(k)
    return out


def request_tree(ident: str) -> Optional[dict]:
    """Assemble the span tree for a request id OR trace id — completed
    ring first (most recent wins), then the live table. Backs
    ``GET /debug/requests/<id>``."""
    with _lock:
        for r in reversed(_ring):
            if ident in (r.get("request_id"), r.get("trace_id")):
                return _assemble(r.get("trace_id"),
                                 list(r.get("spans", ())), dict(r))
        spans = _live.get(ident)
        if spans is not None:
            return _assemble(ident, list(spans))
        for tid, spans in _live.items():
            if any((s.get("args") or {}).get("request_id") == ident
                   for s in spans):
                return _assemble(tid, list(spans))
    return None


def summary() -> dict:
    """Recorder state for ``GET /debug/flight``: recent completed
    requests (ids + outcome, no span bodies), trigger history, bundle
    paths written by this process."""
    with _lock:
        ring = [{k: r.get(k) for k in ("request_id", "trace_id", "ok",
                                       "code", "latency_ms", "kind", "ts")}
                for r in _ring]
        return {
            "enabled": _enabled,
            "dir": _dir(),
            "ring": ring,
            "live_traces": len(_live),
            "triggers": list(_triggers),
            "bundles": list(_bundles_written),
        }


def reset():
    """Drop all recorder state and re-read the env knobs (tests)."""
    global _ring, _seq, _enabled
    with _lock:
        _live.clear()
        _ring = deque(maxlen=max(1, int(
            os.environ.get("MXNET_FLIGHT_RING", "256"))))
        _bundles_written.clear()
        _triggers.clear()
        _seq = 0
    _enabled = (os.environ.get("MXNET_FLIGHT_RECORDER", "1") != "0"
                and os.environ.get("MXNET_TELEMETRY", "1") != "0")
