"""mxnet_tpu.telemetry — process-wide tracing + metrics (ISSUE 4).

Four pieces, all with branch-and-return disabled paths:

- **tracing** (:mod:`.tracer`): per-thread ring-buffer span recorder.
  Spans are OFF by default; enable domains with
  ``MXNET_PROFILER=engine,serving,kvstore`` (or ``all``), or
  programmatically via :func:`enable_spans`. ``profiler.dump_profile()``
  drains every buffer into a chrome://tracing JSON.
- **metrics** (:mod:`.metrics`): the central :data:`registry` of
  counters/gauges/histograms plus adopted metric groups (ServingMetrics
  et al.), with ``get_name_value()`` and Prometheus ``exposition()``
  (histograms carry OpenMetrics exemplars linking buckets to traces).
  Counters are ON by default; ``MXNET_TELEMETRY=0`` kills everything.
- **trace context** (:mod:`.context`): W3C ``traceparent`` parse/mint
  at the HTTP edge, thread-local + object carry through serving and
  the PS plane, ``trace_id``/``span_id``/``parent_id`` span stamps.
- **flight recorder** (:mod:`.flight`): always-on bounded ring of
  completed request timelines; SLO anomalies (deadline miss, shed,
  compile-after-steady, drain, ``MXNET_SLOW_REQUEST_MS``) write
  diagnostic bundles to ``MXNET_FLIGHT_DIR``.

See docs/observability.md. Instrumentation must live OUTSIDE
jitted/shard_mapped functions — enforced by
``mxnet_tpu.analysis.trace_purity`` (rule ``telemetry-in-jit``), which
also flags ``current_context()`` reads inside jitted code.
"""
from .tracer import (begin, chrome_events, clock_ns, complete,
                     disable_spans, drain_events, dump_ring, enable_spans,
                     enabled, enabled_domains, end, instant, mark_begin,
                     mark_end, reset, set_span_sink, span)
from .metrics import (CONTENT_TYPE_LATEST, Counter, Gauge, Histogram,
                      Registry, registry)
from . import context
from . import flight
from .context import TraceContext, current_context

__all__ = [
    "span", "begin", "end", "complete", "instant", "mark_begin", "mark_end",
    "enabled", "enable_spans", "disable_spans", "enabled_domains",
    "drain_events", "chrome_events", "clock_ns", "reset", "dump_ring",
    "set_span_sink",
    "registry", "Registry", "Counter", "Gauge", "Histogram",
    "CONTENT_TYPE_LATEST",
    "context", "flight", "TraceContext", "current_context",
]
