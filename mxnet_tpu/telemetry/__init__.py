"""mxnet_tpu.telemetry — process-wide tracing + metrics (ISSUE 4).

Two halves, both with branch-and-return disabled paths:

- **tracing** (:mod:`.tracer`): per-thread ring-buffer span recorder.
  Spans are OFF by default; enable domains with
  ``MXNET_PROFILER=engine,serving,kvstore`` (or ``all``), or
  programmatically via :func:`enable_spans`. ``profiler.dump_profile()``
  drains every buffer into a chrome://tracing JSON.
- **metrics** (:mod:`.metrics`): the central :data:`registry` of
  counters/gauges/histograms plus adopted metric groups (ServingMetrics
  et al.), with ``get_name_value()`` and Prometheus ``exposition()``.
  Counters are ON by default; ``MXNET_TELEMETRY=0`` kills everything.

See docs/observability.md. Instrumentation must live OUTSIDE
jitted/shard_mapped functions — enforced by
``mxnet_tpu.analysis.trace_purity`` (rule ``telemetry-in-jit``).
"""
from .tracer import (begin, chrome_events, clock_ns, complete,
                     disable_spans, drain_events, enable_spans, enabled,
                     enabled_domains, end, instant, mark_begin, mark_end,
                     reset, span)
from .metrics import (CONTENT_TYPE_LATEST, Counter, Gauge, Histogram,
                      Registry, registry)

__all__ = [
    "span", "begin", "end", "complete", "instant", "mark_begin", "mark_end",
    "enabled", "enable_spans", "disable_spans", "enabled_domains",
    "drain_events", "chrome_events", "clock_ns", "reset",
    "registry", "Registry", "Counter", "Gauge", "Histogram",
    "CONTENT_TYPE_LATEST",
]
