"""Notebook training-visualization callbacks.

Capability parity with python/mxnet/notebook/callback.py (reference
:54-350): ``PandasLogger`` accumulates per-batch/epoch metrics into pandas
DataFrames for notebook analysis/plotting; ``LiveLearningCurve`` is the
live-plot variant (requires a display backend; here it reuses the same
accumulation and exposes the dataframes). Dependencies are imported
lazily and failures degrade to plain-dict storage.
"""
from __future__ import annotations

import time


def _try_pandas():
    try:
        import pandas as pd
        return pd
    except Exception:
        return None


class PandasLogger(object):
    """Log train/eval metrics into pandas DataFrames
    (reference notebook/callback.py:54-170).

    Hook the instance's ``train_cb``/``eval_cb``/``epoch_cb`` methods into
    ``Module.fit``'s batch_end/eval_end/epoch_end callbacks.
    """

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._start = time.time()
        self._records = {"train": [], "eval": [], "epoch": []}
        self._pd = _try_pandas()

    def _df(self, name):
        rows = self._records[name]
        if self._pd is None:
            return rows
        return self._pd.DataFrame(rows)

    @property
    def train_df(self):
        return self._df("train")

    @property
    def eval_df(self):
        return self._df("eval")

    @property
    def epoch_df(self):
        return self._df("epoch")

    @property
    def all_dataframes(self):
        return {k: self._df(k) for k in self._records}

    def elapsed(self):
        return time.time() - self._start

    def append_metrics(self, metrics, df_name):
        row = dict(metrics)
        row["elapsed"] = self.elapsed()
        self._records[df_name].append(row)

    def train_cb(self, param):
        """batch_end_callback for training metrics."""
        if param.nbatch % self.frequent != 0 or param.eval_metric is None:
            return
        metrics = dict(param.eval_metric.get_name_value())
        metrics["epoch"] = param.epoch
        metrics["nbatch"] = param.nbatch
        self.append_metrics(metrics, "train")

    def eval_cb(self, param):
        """eval_end_callback for validation metrics."""
        if param.eval_metric is None:
            return
        metrics = dict(param.eval_metric.get_name_value())
        metrics["epoch"] = param.epoch
        self.append_metrics(metrics, "eval")

    def epoch_cb(self, epoch=None, symbol=None, arg_params=None,
                 aux_params=None):
        """epoch_end_callback stamping epoch wall time."""
        self.append_metrics({"epoch": epoch}, "epoch")


class LiveLearningCurve(PandasLogger):
    """Accumulating learning-curve callback (reference
    notebook/callback.py:172-350 draws with bokeh; headless builds keep
    the same data surface and leave rendering to the notebook)."""

    def __init__(self, metric_name="accuracy", frequent=50, batch_size=1):
        super().__init__(batch_size=batch_size, frequent=frequent)
        self.metric_name = metric_name
