"""Notebook helpers (reference python/mxnet/notebook/)."""
from . import callback
