"""Profiler controls.

TPU-native analogue of python/mxnet/profiler.py + src/engine/profiler.cc
(SURVEY §5.1). The reference stamps per-op begin/end in engine workers
and dumps chrome://tracing JSON (MXDumpProfile). Here the host half of
that picture comes from :mod:`mxnet_tpu.telemetry` (per-thread span ring
buffers instrumenting the engine, serving, kvstore and executor layers)
plus the engine's own per-op events; the device half is a jax.profiler
trace (XLA → TensorBoard/perfetto). ``dump_profile()`` merges all of it
into ONE chrome://tracing-loadable JSON file — and it ALWAYS writes that
file at the configured ``filename`` (logging the path), even when the
jax trace was never started and even with zero host events, so a
CPU-only run has real output (docs/observability.md).
"""
from __future__ import annotations

import glob
import gzip
import json
import logging
import os

from . import telemetry

_log = logging.getLogger("mxnet_tpu")

_state = {"running": False, "dir": None, "filename": "profile.json",
          "jax": False, "engine_prof": False, "prev_domains": None}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(reference profiler.py profiler_set_config / MXSetProfilerConfig)."""
    _state["filename"] = filename
    _state["dir"] = os.path.dirname(os.path.abspath(filename)) or "."


def profiler_set_state(state="stop"):
    """(reference profiler.py profiler_set_state / MXSetProfilerState).

    ``'run'`` enables host telemetry spans (every domain unless
    ``MXNET_PROFILER`` names a subset), turns on the engine's per-op
    profiling, and — unless ``MXNET_PROFILER_JAX=0`` — starts a
    jax.profiler trace under ``<dir>/jax_trace``. ``'stop'`` ends the
    window; ``dump_profile()`` flushes everything to one JSON file."""
    if state == "run" and not _state["running"]:
        _state["prev_domains"] = (telemetry.enabled_domains()
                                  if telemetry.enabled_domains() else None)
        telemetry.enable_spans(os.environ.get("MXNET_PROFILER") or "all")
        telemetry.mark_begin("mxnet_profile", domain="profiler")
        try:
            from . import engine

            engine.get().set_profiling(True)
            _state["engine_prof"] = True
        except Exception:
            _state["engine_prof"] = False
        if os.environ.get("MXNET_PROFILER_JAX", "1") != "0":
            try:
                import jax

                trace_dir = (_state["dir"] or ".") + "/jax_trace"
                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
                _state["jax"] = True
            except Exception:
                _log.exception("jax.profiler trace failed to start; "
                               "host-span profiling continues")
                _state["jax"] = False
        _state["running"] = True
    elif state == "stop" and _state["running"]:
        telemetry.mark_end("mxnet_profile", domain="profiler")
        if _state["jax"]:
            import jax

            jax.profiler.stop_trace()
            _state["jax"] = False
            _log.info("profiler trace written under %s/jax_trace",
                      _state["dir"] or ".")
        if _state["engine_prof"]:
            try:
                from . import engine

                engine.get().set_profiling(False)
            except Exception:
                pass
        if _state["prev_domains"]:
            telemetry.enable_spans(_state["prev_domains"])
        else:
            telemetry.disable_spans()
        _state["running"] = False


def _ring_file_events(dirs):
    """Merge span ring files dumped by OTHER processes
    (``telemetry.dump_ring()`` — PS servers, launcher-spawned workers
    write ``telemetry_ring_<pid>.json``). Each file's events are already
    chrome-format; pid tags keep their rows separate in the viewer, and
    trace-stamped spans join the same trace_id across processes. Files
    are consumed (removed) so a second dump only sees newer rings."""
    events = []
    seen = set()
    for d in dirs:
        if not d or d in seen:
            continue
        seen.add(d)
        for path in sorted(glob.glob(
                os.path.join(d, "telemetry_ring_*.json"))):
            try:
                with open(path) as f:
                    data = json.load(f)
                evs = (data if isinstance(data, list)
                       else data.get("traceEvents", [])
                       if isinstance(data, dict) else [])
                events.extend(e for e in evs if isinstance(e, dict))
                os.remove(path)
            except (OSError, ValueError):
                continue
    return events


def _jax_trace_events(trace_dir: str):
    """Best-effort: pull traceEvents out of the jax/XLA trace artifacts
    (``*.trace.json.gz`` under the TensorBoard plugin layout) so device
    and host events share one timeline file."""
    events = []
    try:
        for path in glob.glob(os.path.join(trace_dir, "**", "*.trace.json*"),
                              recursive=True):
            try:
                opener = gzip.open if path.endswith(".gz") else open
                with opener(path, "rt") as f:
                    data = json.load(f)
                evs = data.get("traceEvents", []) \
                    if isinstance(data, dict) else []
                events.extend(e for e in evs if isinstance(e, dict))
            except Exception:
                continue
    except Exception:
        pass
    return events


def dump_profile() -> str:
    """(reference MXDumpProfile) — stop the window if running and write
    the merged chrome://tracing JSON at the configured ``filename``.

    Always writes (zero events included) and returns the absolute path;
    host spans come from ``telemetry`` (drained — a second dump only
    contains newer events), engine per-op events from the native/python
    engine profiler when it was on, device events from the jax trace dir
    when one exists."""
    if _state["running"]:
        profiler_set_state("stop")
    path = os.path.abspath(_state["filename"])
    events = telemetry.chrome_events(clear=True)
    n_host = len(events)
    if _state["engine_prof"]:
        try:
            from . import engine

            events.extend(engine.get().dump_profile().get("traceEvents", []))
        except Exception:
            pass
        _state["engine_prof"] = False
    events.extend(_ring_file_events(
        [_state["dir"], os.environ.get("MXNET_TELEMETRY_RING_DIR")]))
    events.extend(_jax_trace_events((_state["dir"] or ".") + "/jax_trace"))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    _log.info("profile dumped to %s (%d events, %d host spans)",
              path, len(events), n_host)
    return path


class TraceAnnotation:
    """Named region annotation visible in the trace (reference per-op
    OprExecStat naming; here jax.profiler.TraceAnnotation)."""

    def __init__(self, name, **kwargs):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(name, **kwargs)

    def __enter__(self):
        return self._ctx.__enter__()

    def __exit__(self, *a):
        return self._ctx.__exit__(*a)
