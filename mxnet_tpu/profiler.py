"""Profiler controls.

TPU-native analogue of python/mxnet/profiler.py + src/engine/profiler.cc
(SURVEY §5.1). The reference stamps per-op begin/end in engine workers and
dumps chrome://tracing JSON. Here the equivalent machinery is jax.profiler
(XLA traces → TensorBoard/perfetto, which chrome://tracing reads); this
module preserves the reference API surface and maps it onto jax.profiler.
"""
from __future__ import annotations

import logging
import os

_state = {"running": False, "dir": None, "filename": "profile.json"}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(reference profiler.py profiler_set_config / MXSetProfilerConfig)."""
    _state["filename"] = filename
    _state["dir"] = os.path.dirname(os.path.abspath(filename)) or "."


def profiler_set_state(state="stop"):
    """(reference profiler.py profiler_set_state / MXSetProfilerState).
    'run' starts a jax.profiler trace; 'stop' ends it and writes the trace
    directory next to the configured filename."""
    import jax

    if state == "run" and not _state["running"]:
        trace_dir = (_state["dir"] or ".") + "/jax_trace"
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        _state["running"] = True
    elif state == "stop" and _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False
        logging.info("profiler trace written under %s/jax_trace", _state["dir"] or ".")


def dump_profile():
    """(reference MXDumpProfile) — stop and flush."""
    if _state["running"]:
        profiler_set_state("stop")


class TraceAnnotation:
    """Named region annotation visible in the trace (reference per-op
    OprExecStat naming; here jax.profiler.TraceAnnotation)."""

    def __init__(self, name, **kwargs):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(name, **kwargs)

    def __enter__(self):
        return self._ctx.__enter__()

    def __exit__(self, *a):
        return self._ctx.__exit__(*a)
