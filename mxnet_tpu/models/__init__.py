"""Model zoo — symbolic network definitions with capability parity to the
reference's example/image-classification/symbols/ + example/rnn/.

Each builder returns a Symbol whose head is a SoftmaxOutput named
``softmax`` so every model drops into ``Module.fit`` / ``FeedForward``
unchanged (reference example/image-classification/train_model.py pattern).

Factory: ``get_symbol(name, num_classes=..., **kwargs)``.
"""
from . import mlp
from . import lenet
from . import alexnet
from . import vgg
from . import inception_bn
from . import inception_v3
from . import inception_resnet_v2
from . import resnet
from . import resnext
from . import googlenet
from . import lstm_lm
from . import transformer
from . import ssd

_BUILDERS = {
    "mlp": mlp.get_symbol,
    "lenet": lenet.get_symbol,
    "alexnet": alexnet.get_symbol,
    "vgg": vgg.get_symbol,
    "vgg16": lambda **kw: vgg.get_symbol(num_layers=16, **kw),
    "vgg19": lambda **kw: vgg.get_symbol(num_layers=19, **kw),
    "googlenet": googlenet.get_symbol,
    "inception-v1": googlenet.get_symbol,
    "inception-bn": inception_bn.get_symbol,
    "inception-v3": inception_v3.get_symbol,
    "inception-resnet-v2": inception_resnet_v2.get_symbol,
    "resnet": resnet.get_symbol,
    "resnet-18": lambda **kw: resnet.get_symbol(num_layers=18, **kw),
    "resnet-34": lambda **kw: resnet.get_symbol(num_layers=34, **kw),
    "resnet-50": lambda **kw: resnet.get_symbol(num_layers=50, **kw),
    "resnet-101": lambda **kw: resnet.get_symbol(num_layers=101, **kw),
    "resnet-152": lambda **kw: resnet.get_symbol(num_layers=152, **kw),
    "resnext": resnext.get_symbol,
    "resnext-50": lambda **kw: resnext.get_symbol(num_layers=50, **kw),
    "resnext-101": lambda **kw: resnext.get_symbol(num_layers=101, **kw),
    "resnext-152": lambda **kw: resnext.get_symbol(num_layers=152, **kw),
    "lstm-lm": lstm_lm.get_symbol,
    "transformer-lm": transformer.get_symbol,
    "ssd-vgg16": ssd.get_symbol,
}


def get_symbol(name, **kwargs):
    key = name.lower()
    if key not in _BUILDERS:
        raise ValueError(
            "unknown model %r; available: %s" % (name, sorted(_BUILDERS)))
    return _BUILDERS[key](**kwargs)
