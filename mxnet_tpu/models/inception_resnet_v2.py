"""Inception-ResNet-v2 (reference example/image-classification/symbols/
inception-resnet-v2.py; Szegedy et al., arXiv:1602.07261): residual
inception blocks (35/17/8) scaled into the trunk, stem + two reduction
towers, 1536-d head.

The reference file's quirks are reproduced deliberately — block17's
129-channel tower (a known typo in the published symbol, kept so shapes
match its checkpoints) and the scale-times-tower residual adds."""
from .. import symbol as sym


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
          with_act=True):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad)
    b = sym.BatchNorm(data=c)
    return sym.Activation(data=b, act_type="relu") if with_act else b


def _block35(net, in_ch, scale):
    t0 = _conv(net, 32, (1, 1))
    t1 = _conv(_conv(net, 32, (1, 1)), 32, (3, 3), pad=(1, 1))
    t2 = _conv(net, 32, (1, 1))
    t2 = _conv(t2, 48, (3, 3), pad=(1, 1))
    t2 = _conv(t2, 64, (3, 3), pad=(1, 1))
    mixed = sym.Concat(t0, t1, t2)
    out = _conv(mixed, in_ch, (1, 1), with_act=False)
    return sym.Activation(net + scale * out, act_type="relu")


def _block17(net, in_ch, scale):
    t0 = _conv(net, 192, (1, 1))
    t1 = _conv(net, 129, (1, 1))       # sic: the reference's 129
    t1 = _conv(t1, 160, (1, 7), pad=(1, 2))
    t1 = _conv(t1, 192, (7, 1), pad=(2, 1))
    mixed = sym.Concat(t0, t1)
    out = _conv(mixed, in_ch, (1, 1), with_act=False)
    return sym.Activation(net + scale * out, act_type="relu")


def _block8(net, in_ch, scale, with_act=True):
    t0 = _conv(net, 192, (1, 1))
    t1 = _conv(net, 192, (1, 1))
    t1 = _conv(t1, 224, (1, 3), pad=(0, 1))
    t1 = _conv(t1, 256, (3, 1), pad=(1, 0))
    mixed = sym.Concat(t0, t1)
    out = _conv(mixed, in_ch, (1, 1), with_act=False)
    net = net + scale * out
    return sym.Activation(net, act_type="relu") if with_act else net


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    x = _conv(data, 32, (3, 3), stride=(2, 2))
    x = _conv(x, 32, (3, 3))
    x = _conv(x, 64, (3, 3), pad=(1, 1))
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 80, (1, 1))
    x = _conv(x, 192, (3, 3))
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")

    # mixed 5b
    t0 = _conv(x, 96, (1, 1))
    t1 = _conv(_conv(x, 48, (1, 1)), 64, (5, 5), pad=(2, 2))
    t2 = _conv(x, 64, (1, 1))
    t2 = _conv(t2, 96, (3, 3), pad=(1, 1))
    t2 = _conv(t2, 96, (3, 3), pad=(1, 1))
    t3 = sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg")
    t3 = _conv(t3, 64, (1, 1))
    net = sym.Concat(t0, t1, t2, t3)               # 320 ch

    for _ in range(10):
        net = _block35(net, 320, scale=0.17)

    # reduction A
    t0 = _conv(net, 384, (3, 3), stride=(2, 2))
    t1 = _conv(net, 256, (1, 1))
    t1 = _conv(t1, 256, (3, 3), pad=(1, 1))
    t1 = _conv(t1, 384, (3, 3), stride=(2, 2))
    tp = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max")
    net = sym.Concat(t0, t1, tp)                   # 1088 ch

    for _ in range(20):
        net = _block17(net, 1088, scale=0.1)

    # reduction B
    t0 = _conv(_conv(net, 256, (1, 1)), 384, (3, 3), stride=(2, 2))
    t1 = _conv(_conv(net, 256, (1, 1)), 288, (3, 3), stride=(2, 2))
    t2 = _conv(net, 256, (1, 1))
    t2 = _conv(t2, 288, (3, 3), pad=(1, 1))
    t2 = _conv(t2, 320, (3, 3), stride=(2, 2))
    tp = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max")
    net = sym.Concat(t0, t1, t2, tp)               # 2080 ch

    for _ in range(9):
        net = _block8(net, 2080, scale=0.2)
    # the reference runs the FINAL, non-activated block8 at full scale
    net = _block8(net, 2080, scale=1.0, with_act=False)

    net = _conv(net, 1536, (1, 1))
    net = sym.Pooling(net, kernel=(1, 1), global_pool=True,
                      pool_type="avg")
    net = sym.Flatten(net)
    net = sym.Dropout(net, p=0.2)
    net = sym.FullyConnected(net, num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name="softmax")
