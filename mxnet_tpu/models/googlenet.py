"""GoogLeNet / Inception-v1 (reference example/image-classification/
symbols/googlenet.py — the Going Deeper with Convolutions topology,
InceptionFactory blocks, no BatchNorm)."""
from .. import symbol as sym


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None,
          suffix=''):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad,
                        name='conv_%s%s' % (name, suffix))
    return sym.Activation(data=c, act_type='relu',
                          name='relu_%s%s' % (name, suffix))


def _inception(data, n1, n3r, n3, n5r, n5, proj, name, pool='max'):
    """The InceptionFactory block: 1x1 / 1x1->3x3 / 1x1->5x5 /
    pool->1x1-proj branches, channel-concatenated."""
    c1 = _conv(data, n1, (1, 1), name='%s_1x1' % name)
    c3 = _conv(data, n3r, (1, 1), name='%s_3x3' % name, suffix='_reduce')
    c3 = _conv(c3, n3, (3, 3), pad=(1, 1), name='%s_3x3' % name)
    c5 = _conv(data, n5r, (1, 1), name='%s_5x5' % name, suffix='_reduce')
    c5 = _conv(c5, n5, (5, 5), pad=(2, 2), name='%s_5x5' % name)
    p = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type=pool, name='%s_pool_%s' % (pool, name))
    p = _conv(p, proj, (1, 1), name='%s_proj' % name)
    return sym.Concat(c1, c3, c5, p, name='ch_concat_%s' % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    # names and pooling conventions follow the reference symbol file
    # EXACTLY (conv1..conv3, in3a..in5b, unnamed-FC auto-name) so
    # reference-trained checkpoints load by parameter name
    x = _conv(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="conv1")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 64, (1, 1), name="conv2")
    x = _conv(x, 192, (3, 3), pad=(1, 1), name="conv3")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _inception(x, 64, 96, 128, 16, 32, 32, "in3a")
    x = _inception(x, 128, 128, 192, 32, 96, 64, "in3b")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _inception(x, 192, 96, 208, 16, 48, 64, "in4a")
    x = _inception(x, 160, 112, 224, 24, 64, 64, "in4b")
    x = _inception(x, 128, 128, 256, 24, 64, 64, "in4c")
    x = _inception(x, 112, 144, 288, 32, 64, 64, "in4d")
    x = _inception(x, 256, 160, 320, 32, 128, 128, "in4e")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _inception(x, 256, 160, 320, 32, 128, 128, "in5a")
    x = _inception(x, 384, 192, 384, 48, 128, 128, "in5b")
    # the reference's fixed 7x7 avg kernel assumes a 7x7 final map (its
    # Caffe ceil-mode lineage); global-avg is shape-robust and identical
    # when the map IS the kernel size
    x = sym.Pooling(x, kernel=(7, 7), stride=(1, 1), pool_type="avg",
                    global_pool=True)
    x = sym.Flatten(x)
    # the reference leaves this FullyConnected unnamed; pin its
    # auto-name so checkpoint keys line up regardless of build order
    x = sym.FullyConnected(x, num_hidden=num_classes,
                           name="fullyconnected0")
    return sym.SoftmaxOutput(x, name="softmax")
