"""ResNet v2 (pre-activation) — the flagship benchmark model.

Capability parity with the reference's
example/image-classification/symbols/resnet.py (He et al. "Identity Mappings
in Deep Residual Networks"), re-expressed on the TPU-native Symbol API.
Depths 18/34 use basic blocks; 50/101/152 use bottlenecks.

TPU notes: all convs are NCHW symbols lowered by XLA to MXU
convolutions; BatchNorm carries functional aux state (moving mean/var)
threaded by the executor.
"""
from .. import symbol as sym


def _resunit(data, num_filter, stride, dim_match, name, bottle_neck,
             bn_mom=0.9, workspace=256):
    """One pre-activation residual unit."""
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + '_bn1')
    act1 = sym.Activation(data=bn1, act_type='relu', name=name + '_relu1')
    if bottle_neck:
        conv1 = sym.Convolution(data=act1, num_filter=num_filter // 4,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, workspace=workspace,
                                name=name + '_conv1')
        bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + '_bn2')
        act2 = sym.Activation(data=bn2, act_type='relu', name=name + '_relu2')
        conv2 = sym.Convolution(data=act2, num_filter=num_filter // 4,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, workspace=workspace,
                                name=name + '_conv2')
        bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + '_bn3')
        act3 = sym.Activation(data=bn3, act_type='relu', name=name + '_relu3')
        conv3 = sym.Convolution(data=act3, num_filter=num_filter,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, workspace=workspace,
                                name=name + '_conv3')
        body = conv3
    else:
        conv1 = sym.Convolution(data=act1, num_filter=num_filter,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, workspace=workspace,
                                name=name + '_conv1')
        bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + '_bn2')
        act2 = sym.Activation(data=bn2, act_type='relu', name=name + '_relu2')
        conv2 = sym.Convolution(data=act2, num_filter=num_filter,
                                kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                                no_bias=True, workspace=workspace,
                                name=name + '_conv2')
        body = conv2
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(data=act1, num_filter=num_filter,
                                   kernel=(1, 1), stride=stride, no_bias=True,
                                   workspace=workspace, name=name + '_sc')
    return body + shortcut


_DEPTH_CONFIG = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               bn_mom=0.9, workspace=256, dtype='float32'):
    if num_layers not in _DEPTH_CONFIG:
        raise ValueError("unsupported resnet depth %d" % num_layers)
    units, bottle_neck = _DEPTH_CONFIG[num_layers]
    filter_list = ([64, 256, 512, 1024, 2048] if bottle_neck
                   else [64, 64, 128, 256, 512])

    data = sym.Variable(name='data')
    if dtype != 'float32':
        data = sym.Cast(data=data, dtype=dtype)
    data = sym.BatchNorm(data=data, fix_gamma=True, eps=2e-5,
                         momentum=bn_mom, name='bn_data')
    height = image_shape[1]
    if height <= 32:  # CIFAR-style stem
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, workspace=workspace, name='conv0')
    else:  # ImageNet stem
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, workspace=workspace, name='conv0')
        body = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                             momentum=bn_mom, name='bn0')
        body = sym.Activation(data=body, act_type='relu', name='relu0')
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type='max', name='pool0')

    for stage in range(4):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _resunit(body, filter_list[stage + 1], stride, False,
                        'stage%d_unit1' % (stage + 1), bottle_neck,
                        bn_mom, workspace)
        for unit in range(units[stage] - 1):
            body = _resunit(body, filter_list[stage + 1], (1, 1), True,
                            'stage%d_unit%d' % (stage + 1, unit + 2),
                            bottle_neck, bn_mom, workspace)

    bn1 = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name='bn1')
    relu1 = sym.Activation(data=bn1, act_type='relu', name='relu1')
    pool1 = sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7),
                        pool_type='avg', name='pool1')
    flat = sym.Flatten(data=pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name='fc1')
    if dtype != 'float32':
        fc1 = sym.Cast(data=fc1, dtype='float32')
    return sym.SoftmaxOutput(data=fc1, name='softmax')
