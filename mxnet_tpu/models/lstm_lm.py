"""LSTM language model (PTB) — BASELINE config 3, reference
example/rnn/lstm_bucketing.py. Embedding → stacked LSTM (unrolled) →
per-step FC → softmax over the flattened (batch*time) axis.

On TPU the unrolled graph compiles to ONE XLA computation; for long
sequences prefer FusedRNNCell, whose scan-based kernel is the cuDNN-RNN
analogue (SURVEY §5.7).
"""
from .. import symbol as sym
from ..rnn import rnn_cell


def get_symbol(num_classes=10000, seq_len=35, num_embed=200, num_hidden=200,
               num_layers=2, dropout=0.0, fused=False, **kwargs):
    data = sym.Variable('data')          # (batch, seq_len) int ids
    embed = sym.Embedding(data=data, input_dim=num_classes,
                          output_dim=num_embed, name='embed')

    if fused:
        stack = rnn_cell.FusedRNNCell(num_hidden, num_layers=num_layers,
                                      mode='lstm', dropout=dropout,
                                      prefix='lstm_')
    else:
        stack = rnn_cell.SequentialRNNCell()
        for i in range(num_layers):
            stack.add(rnn_cell.LSTMCell(num_hidden, prefix='lstm_l%d_' % i))
            if dropout > 0 and i < num_layers - 1:
                stack.add(rnn_cell.DropoutCell(dropout, prefix='drop_l%d_' % i))

    outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True,
                              layout='NTC')
    pred = sym.Reshape(data=outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(data=pred, num_hidden=num_classes, name='pred')
    label = sym.Variable('softmax_label')
    label = sym.Reshape(data=label, shape=(-1,))
    return sym.SoftmaxOutput(data=pred, label=label, name='softmax')
