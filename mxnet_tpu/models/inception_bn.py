"""Inception-BN (reference example/image-classification/symbols/inception-bn.py)."""
from .. import symbol as sym


def _conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                  name=None, suffix=''):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad,
                           name='conv_%s%s' % (name, suffix))
    bn = sym.BatchNorm(data=conv, fix_gamma=False,
                       name='bn_%s%s' % (name, suffix))
    return sym.Activation(data=bn, act_type='relu',
                          name='relu_%s%s' % (name, suffix))


def _inception_a(data, n1, n3r, n3, nd3r, nd3, pool, proj, name):
    c1 = _conv_factory(data, n1, (1, 1), name=('%s_1x1' % name))
    c3 = _conv_factory(data, n3r, (1, 1), name=('%s_3x3' % name), suffix='_reduce')
    c3 = _conv_factory(c3, n3, (3, 3), pad=(1, 1), name=('%s_3x3' % name))
    cd3 = _conv_factory(data, nd3r, (1, 1), name=('%s_double_3x3' % name),
                        suffix='_reduce')
    cd3 = _conv_factory(cd3, nd3, (3, 3), pad=(1, 1),
                        name=('%s_double_3x3_0' % name))
    cd3 = _conv_factory(cd3, nd3, (3, 3), pad=(1, 1),
                        name=('%s_double_3x3_1' % name))
    p = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type=pool, name=('%s_pool_%s_pool' % (pool, name)))
    p = _conv_factory(p, proj, (1, 1), name=('%s_proj' % name))
    return sym.Concat(c1, c3, cd3, p, name='ch_concat_%s_chconcat' % name)


def _inception_b(data, n3r, n3, nd3r, nd3, name):
    c3 = _conv_factory(data, n3r, (1, 1), name=('%s_3x3' % name), suffix='_reduce')
    c3 = _conv_factory(c3, n3, (3, 3), pad=(1, 1), stride=(2, 2),
                       name=('%s_3x3' % name))
    cd3 = _conv_factory(data, nd3r, (1, 1), name=('%s_double_3x3' % name),
                        suffix='_reduce')
    cd3 = _conv_factory(cd3, nd3, (3, 3), pad=(1, 1),
                        name=('%s_double_3x3_0' % name))
    cd3 = _conv_factory(cd3, nd3, (3, 3), pad=(1, 1), stride=(2, 2),
                        name=('%s_double_3x3_1' % name))
    p = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                    pool_type='max', name=('max_pool_%s_pool' % name))
    return sym.Concat(c3, cd3, p, name='ch_concat_%s_chconcat' % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable('data')
    body = _conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name='1')
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                       pool_type='max', name='pool_1', pad=(1, 1))
    body = _conv_factory(body, 64, (1, 1), name='2_red')
    body = _conv_factory(body, 192, (3, 3), pad=(1, 1), name='2')
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                       pool_type='max', name='pool_2', pad=(1, 1))
    body = _inception_a(body, 64, 64, 64, 64, 96, 'avg', 32, '3a')
    body = _inception_a(body, 64, 64, 96, 64, 96, 'avg', 64, '3b')
    body = _inception_b(body, 128, 160, 64, 96, '3c')
    body = _inception_a(body, 224, 64, 96, 96, 128, 'avg', 128, '4a')
    body = _inception_a(body, 192, 96, 128, 96, 128, 'avg', 128, '4b')
    body = _inception_a(body, 160, 128, 160, 128, 160, 'avg', 128, '4c')
    body = _inception_a(body, 96, 128, 192, 160, 192, 'avg', 128, '4d')
    body = _inception_b(body, 128, 192, 192, 256, '4e')
    body = _inception_a(body, 352, 192, 320, 160, 224, 'avg', 128, '5a')
    body = _inception_a(body, 352, 192, 320, 192, 224, 'max', 128, '5b')
    pool = sym.Pooling(data=body, kernel=(7, 7), stride=(1, 1),
                       global_pool=True, pool_type='avg', name='global_pool')
    flat = sym.Flatten(data=pool, name='flatten')
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name='fc1')
    return sym.SoftmaxOutput(data=fc1, name='softmax')
