"""Decoder-only transformer LM on the Symbol API.

The framework's modern long-sequence model (SURVEY §5.7: the idiomatic
replacement for unrolled RNNs). Attention lowers to the Pallas flash kernel
on TPU (ops/attention.py → ops/pallas/flash_attention.py); the sharded
functional twin used for tp/pp/sp training lives in
mxnet_tpu.parallel.transformer.
"""
from .. import symbol as sym


def _block(x, num_heads, dm, dff, name, num_kv_heads=0, use_flash=None):
    ln1_g = sym.Variable(name + '_ln1_gamma', shape=(dm,))
    ln1_b = sym.Variable(name + '_ln1_beta', shape=(dm,))
    h = sym.LayerNorm(data=x, gamma=ln1_g, beta=ln1_b, name=name + '_ln1')
    # GQA (num_kv_heads < num_heads): k/v projections shrink to
    # num_kv_heads*head_dim and the flash kernel streams them narrow
    dkv = dm if not num_kv_heads else dm // num_heads * num_kv_heads
    q = sym.FullyConnected(data=h, num_hidden=dm, flatten=False, no_bias=True,
                           name=name + '_q')
    k = sym.FullyConnected(data=h, num_hidden=dkv, flatten=False,
                           no_bias=True, name=name + '_k')
    v = sym.FullyConnected(data=h, num_hidden=dkv, flatten=False,
                           no_bias=True, name=name + '_v')
    # use_flash=None defers to the op default (True, with the kernel's
    # own on-TPU/shape selection gate) — passing None through would
    # read as falsy and silently pin the einsum path
    flash_kw = {} if use_flash is None else {'use_flash': use_flash}
    att = sym.MultiHeadAttention(query=q, key=k, value=v, num_heads=num_heads,
                                 num_kv_heads=num_kv_heads, causal=True,
                                 use_rope=True, name=name + '_attn',
                                 **flash_kw)
    att = sym.FullyConnected(data=att, num_hidden=dm, flatten=False,
                             no_bias=True, name=name + '_o')
    x = x + att
    ln2_g = sym.Variable(name + '_ln2_gamma', shape=(dm,))
    ln2_b = sym.Variable(name + '_ln2_beta', shape=(dm,))
    h = sym.LayerNorm(data=x, gamma=ln2_g, beta=ln2_b, name=name + '_ln2')
    h = sym.FullyConnected(data=h, num_hidden=dff, flatten=False,
                           name=name + '_ffn1')
    h = sym.Activation(data=h, act_type='gelu', name=name + '_gelu')
    h = sym.FullyConnected(data=h, num_hidden=dm, flatten=False,
                           name=name + '_ffn2')
    return x + h


def _backbone(num_classes, num_layers, num_heads, model_dim, ffn_dim,
              num_kv_heads, use_flash):
    data = sym.Variable('data')          # (batch, seq_len) int ids
    x = sym.Embedding(data=data, input_dim=num_classes,
                      output_dim=model_dim, name='embed')
    for i in range(num_layers):
        x = _block(x, num_heads, model_dim, ffn_dim, 'layer%d' % i,
                   num_kv_heads=num_kv_heads, use_flash=use_flash)
    lnf_g = sym.Variable('lnf_gamma', shape=(model_dim,))
    lnf_b = sym.Variable('lnf_beta', shape=(model_dim,))
    x = sym.LayerNorm(data=x, gamma=lnf_g, beta=lnf_b, name='lnf')
    pred = sym.Reshape(data=x, shape=(-1, model_dim))
    return sym.FullyConnected(data=pred, num_hidden=num_classes, name='pred')


def get_symbol(num_classes=32000, seq_len=512, num_layers=4, num_heads=8,
               model_dim=512, ffn_dim=2048, num_kv_heads=0, use_flash=None,
               scalar_loss=False, **kwargs):
    """Decoder LM symbol. scalar_loss=True emits a MakeLoss mean-NLL head
    instead of SoftmaxOutput — the (batch*seq, vocab) probability output
    is the right inference surface but costs a fresh device buffer per
    step, which benchmark/training loops that only need the loss avoid
    (docs/perf.md LSTM caveat)."""
    pred = _backbone(num_classes, num_layers, num_heads, model_dim, ffn_dim,
                     num_kv_heads, use_flash)
    label = sym.Reshape(data=sym.Variable('softmax_label'), shape=(-1,))
    if scalar_loss:
        logp = sym.log_softmax(pred, axis=-1)
        onehot = sym.one_hot(label, depth=num_classes)
        nll = sym._mul_scalar(
            sym.mean(sym.sum(sym._mul(logp, onehot), axis=1)), scalar=-1.0)
        return sym.MakeLoss(nll, name='loss')
    return sym.SoftmaxOutput(data=pred, label=label, name='softmax')
