"""SSD detector on a reduced-VGG16 backbone.

Capability parity with the reference's SSD example (example/ssd — the
detection workload of SURVEY §7 S9), built from the contrib multibox ops
(MultiBoxPrior/Target/Detection, src/operator/contrib/multibox_*.cc).

TPU-first layout notes: every scale's class/location heads are plain 3×3
convolutions whose outputs are flattened and concatenated once — one fused
HLO for all heads; anchors come from MultiBoxPrior per scale and concat to
a single (1, A, 4) tensor, so target matching and NMS run over one static
anchor set (no per-scale host loops).

``get_symbol(num_classes, mode='train')`` → training symbol whose outputs
are [cls_prob, loc_loss, cls_target] combined into training losses;
``mode='detect'`` → MultiBoxDetection inference head.
"""
from .. import symbol as sym


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
              stride=(1, 1)):
    c = sym.Convolution(data=data, kernel=kernel, pad=pad, stride=stride,
                        num_filter=num_filter, name=name)
    return sym.Activation(data=c, act_type="relu", name=name + "_relu")


def _backbone(data):
    """Reduced VGG16: conv1_1..conv5_3 (pool5 3×3/1), dilated-fc analogue
    conv6/conv7, then extra pyramid scales conv8/conv9/conv10."""
    feats = []
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512)]
    for i, (n, f) in enumerate(cfg):
        for j in range(n):
            data = _conv_act(data, "conv%d_%d" % (i + 1, j + 1), f)
        data = sym.Pooling(data=data, pool_type="max", kernel=(2, 2),
                           stride=(2, 2), name="pool%d" % (i + 1))
    # conv4_3-equivalent scale (after pool4 here for static simplicity)
    feats.append(data)  # stride 16 feature
    for j in range(3):
        data = _conv_act(data, "conv5_%d" % (j + 1), 512)
    data = sym.Pooling(data=data, pool_type="max", kernel=(2, 2),
                       stride=(2, 2), name="pool5")
    data = _conv_act(data, "conv6", 1024)
    data = _conv_act(data, "conv7", 1024, kernel=(1, 1), pad=(0, 0))
    feats.append(data)  # stride 32
    data = _conv_act(data, "conv8_1", 256, kernel=(1, 1), pad=(0, 0))
    data = _conv_act(data, "conv8_2", 512, stride=(2, 2))
    feats.append(data)  # stride 64
    data = _conv_act(data, "conv9_1", 128, kernel=(1, 1), pad=(0, 0))
    data = _conv_act(data, "conv9_2", 256, stride=(2, 2))
    feats.append(data)  # stride 128
    return feats


_SIZES = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619)]
_RATIOS = [(1.0, 2.0, 0.5)] * 4


def _multibox_layers(feats, num_classes):
    """Per-scale heads → concatenated (cls_preds, loc_preds, anchors)."""
    cls_list, loc_list, anchor_list = [], [], []
    num_cls = num_classes + 1  # + background
    for i, feat in enumerate(feats):
        na = len(_SIZES[i]) + len(_RATIOS[i]) - 1
        cls = sym.Convolution(data=feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=na * num_cls,
                              name="cls_pred_%d" % i)
        # (B, A*C, H, W) -> (B, H*W*A, C): channel-last flatten keeps the
        # per-anchor class vector contiguous
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = sym.Reshape(data=cls, shape=(0, -1, num_cls))
        cls_list.append(cls)
        loc = sym.Convolution(data=feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=na * 4, name="loc_pred_%d" % i)
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc = sym.Reshape(data=loc, shape=(0, -1))
        loc_list.append(loc)
        anchor_list.append(sym.MultiBoxPrior(
            feat, sizes=_SIZES[i], ratios=_RATIOS[i], clip=True,
            name="anchors_%d" % i))
    cls_preds = sym.concat(*cls_list, dim=1, name="cls_preds")
    # MultiBox ops take (B, C, A) class predictions
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1))
    loc_preds = sym.concat(*loc_list, dim=1, name="loc_preds")
    anchors = sym.concat(*anchor_list, dim=1, name="anchors")
    return cls_preds, loc_preds, anchors


def get_symbol(num_classes=20, mode="train", nms_threshold=0.5,
               nms_topk=400, **kwargs):
    data = sym.Variable("data")
    feats = _backbone(data)
    cls_preds, loc_preds, anchors = _multibox_layers(feats, num_classes)

    if mode == "detect":
        cls_prob = sym.softmax(cls_preds, axis=1, name="cls_prob")
        return sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                     nms_threshold=nms_threshold,
                                     nms_topk=nms_topk, name="detection")

    label = sym.Variable("label")
    loc_target, loc_mask, cls_target = sym.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1.0, negative_mining_ratio=3.0, name="multibox_target")
    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1.0, use_ignore=True,
                                 multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = sym._mul(loc_mask, sym._minus(loc_preds, loc_target))
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_diff, scalar=1.0),
                            grad_scale=1.0, normalization="valid",
                            name="loc_loss")
    cls_target_out = sym.BlockGrad(cls_target, name="cls_target")
    return sym.Group([cls_prob, loc_loss, cls_target_out])
