"""ResNeXt (reference example/image-classification/symbols/resnext.py —
Aggregated Residual Transformations): the ResNet bottleneck with the
middle 3x3 as a GROUPED convolution of cardinality ``num_group``; group
width scales the bottleneck channels by cardinality*bottle_width/64."""
from .. import symbol as sym


def _unit(data, num_filter, stride, dim_match, name, num_group, bn_mom,
          workspace):
    # reference resnext.py residual_unit (bottleneck form): channels are
    # 0.5*num_filter through the grouped middle conv
    mid = int(num_filter * 0.5)
    c1 = sym.Convolution(data=data, num_filter=mid, kernel=(1, 1),
                         stride=(1, 1), pad=(0, 0), no_bias=True,
                         workspace=workspace, name=name + '_conv1')
    b1 = sym.BatchNorm(data=c1, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + '_bn1')
    a1 = sym.Activation(data=b1, act_type='relu', name=name + '_relu1')
    c2 = sym.Convolution(data=a1, num_filter=mid, num_group=num_group,
                         kernel=(3, 3), stride=stride, pad=(1, 1),
                         no_bias=True, workspace=workspace,
                         name=name + '_conv2')
    b2 = sym.BatchNorm(data=c2, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + '_bn2')
    a2 = sym.Activation(data=b2, act_type='relu', name=name + '_relu2')
    c3 = sym.Convolution(data=a2, num_filter=num_filter, kernel=(1, 1),
                         stride=(1, 1), pad=(0, 0), no_bias=True,
                         workspace=workspace, name=name + '_conv3')
    b3 = sym.BatchNorm(data=c3, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + '_bn3')
    if dim_match:
        shortcut = data
    else:
        sc = sym.Convolution(data=data, num_filter=num_filter,
                             kernel=(1, 1), stride=stride, no_bias=True,
                             workspace=workspace, name=name + '_sc')
        shortcut = sym.BatchNorm(data=sc, fix_gamma=False, eps=2e-5,
                                 momentum=bn_mom, name=name + '_sc_bn')
    return sym.Activation(data=b3 + shortcut, act_type='relu',
                          name=name + '_relu')


_DEPTH_CONFIG = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def get_symbol(num_classes=1000, num_layers=50, num_group=32,
               image_shape=(3, 224, 224), bn_mom=0.9, workspace=256,
               **kwargs):
    if num_layers not in _DEPTH_CONFIG:
        raise ValueError("unsupported resnext depth %d" % num_layers)
    units = _DEPTH_CONFIG[num_layers]
    filter_list = [64, 256, 512, 1024, 2048]

    if image_shape[1] <= 32:
        raise ValueError(
            "resnext here is the ImageNet 4-stage configuration; the "
            "reference's CIFAR variant uses a different 3-stage layout "
            "(resnext.py num_stages=3) that is out of scope")
    data = sym.Variable(name='data')
    data = sym.BatchNorm(data=data, fix_gamma=True, eps=2e-5,
                         momentum=bn_mom, name='bn_data')
    body = sym.Convolution(data=data, num_filter=filter_list[0],
                           kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                           no_bias=True, workspace=workspace, name='conv0')
    body = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                         momentum=bn_mom, name='bn0')
    body = sym.Activation(data=body, act_type='relu', name='relu0')
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                       pad=(1, 1), pool_type='max', name='pool0')

    for stage in range(4):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _unit(body, filter_list[stage + 1], stride, False,
                     'stage%d_unit1' % (stage + 1), num_group, bn_mom,
                     workspace)
        for unit in range(units[stage] - 1):
            body = _unit(body, filter_list[stage + 1], (1, 1), True,
                         'stage%d_unit%d' % (stage + 1, unit + 2),
                         num_group, bn_mom, workspace)

    pool1 = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                        pool_type='avg', name='pool1')
    flat = sym.Flatten(data=pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name='fc1')
    return sym.SoftmaxOutput(data=fc1, name='softmax')
