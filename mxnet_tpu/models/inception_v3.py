"""Inception-v3 (reference example/image-classification/symbols/inception-v3.py,
Szegedy et al. "Rethinking the Inception Architecture"). 299x299 input."""
from .. import symbol as sym


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=None, suffix=''):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name='%s%s_conv2d' % (name, suffix))
    bn = sym.BatchNorm(data=c, eps=0.001, fix_gamma=True,
                       name='%s%s_batchnorm' % (name, suffix))
    return sym.Activation(data=bn, act_type='relu',
                          name='%s%s_relu' % (name, suffix))


def _pool(data, kernel, stride, pad, pool_type, name):
    return sym.Pooling(data=data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


def _inception7a(data, n1, n5r, n5, n3r, n3, proj, name):
    t1 = _conv(data, n1, name=('%s_conv' % name))
    t5 = _conv(data, n5r, name=('%s_tower' % name), suffix='_conv')
    t5 = _conv(t5, n5, kernel=(5, 5), pad=(2, 2), name=('%s_tower' % name),
               suffix='_conv_1')
    t3 = _conv(data, n3r, name=('%s_tower_1' % name), suffix='_conv')
    t3 = _conv(t3, n3, kernel=(3, 3), pad=(1, 1), name=('%s_tower_1' % name),
               suffix='_conv_1')
    t3 = _conv(t3, n3, kernel=(3, 3), pad=(1, 1), name=('%s_tower_1' % name),
               suffix='_conv_2')
    p = _pool(data, (3, 3), (1, 1), (1, 1), 'avg',
              ('%s_pool_%s_pool' % ('avg', name)))
    cp = _conv(p, proj, name=('%s_tower_2' % name), suffix='_conv')
    return sym.Concat(t1, t5, t3, cp, name='ch_concat_%s_chconcat' % name)


def _inception7b(data, n3, nd3r, nd3, name):
    t3 = _conv(data, n3, kernel=(3, 3), pad=(0, 0), stride=(2, 2),
               name=('%s_conv' % name))
    td3 = _conv(data, nd3r, name=('%s_tower' % name), suffix='_conv')
    td3 = _conv(td3, nd3, kernel=(3, 3), pad=(1, 1),
                name=('%s_tower' % name), suffix='_conv_1')
    td3 = _conv(td3, nd3, kernel=(3, 3), pad=(0, 0), stride=(2, 2),
                name=('%s_tower' % name), suffix='_conv_2')
    p = _pool(data, (3, 3), (2, 2), (0, 0), 'max',
              ('max_pool_%s_pool' % name))
    return sym.Concat(t3, td3, p, name='ch_concat_%s_chconcat' % name)


def _inception7c(data, n1, n7r, n7, nd7r, nd7, proj, name):
    t1 = _conv(data, n1, name=('%s_conv' % name))
    t7 = _conv(data, n7r, name=('%s_tower' % name), suffix='_conv')
    t7 = _conv(t7, n7, kernel=(1, 7), pad=(0, 3), name=('%s_tower' % name),
               suffix='_conv_1')
    t7 = _conv(t7, n7, kernel=(7, 1), pad=(3, 0), name=('%s_tower' % name),
               suffix='_conv_2')
    td7 = _conv(data, nd7r, name=('%s_tower_1' % name), suffix='_conv')
    td7 = _conv(td7, nd7r, kernel=(7, 1), pad=(3, 0),
                name=('%s_tower_1' % name), suffix='_conv_1')
    td7 = _conv(td7, nd7r, kernel=(1, 7), pad=(0, 3),
                name=('%s_tower_1' % name), suffix='_conv_2')
    td7 = _conv(td7, nd7r, kernel=(7, 1), pad=(3, 0),
                name=('%s_tower_1' % name), suffix='_conv_3')
    td7 = _conv(td7, nd7, kernel=(1, 7), pad=(0, 3),
                name=('%s_tower_1' % name), suffix='_conv_4')
    p = _pool(data, (3, 3), (1, 1), (1, 1), 'avg',
              ('avg_pool_%s_pool' % name))
    cp = _conv(p, proj, name=('%s_tower_2' % name), suffix='_conv')
    return sym.Concat(t1, t7, td7, cp, name='ch_concat_%s_chconcat' % name)


def _inception7d(data, n3r, n3, n7r, n7, name):
    t3 = _conv(data, n3r, name=('%s_tower' % name), suffix='_conv')
    t3 = _conv(t3, n3, kernel=(3, 3), pad=(0, 0), stride=(2, 2),
               name=('%s_tower' % name), suffix='_conv_1')
    t7 = _conv(data, n7r, name=('%s_tower_1' % name), suffix='_conv')
    t7 = _conv(t7, n7r, kernel=(1, 7), pad=(0, 3),
               name=('%s_tower_1' % name), suffix='_conv_1')
    t7 = _conv(t7, n7r, kernel=(7, 1), pad=(3, 0),
               name=('%s_tower_1' % name), suffix='_conv_2')
    t7 = _conv(t7, n7, kernel=(3, 3), stride=(2, 2),
               name=('%s_tower_1' % name), suffix='_conv_3')
    p = _pool(data, (3, 3), (2, 2), (0, 0), 'max',
              ('max_pool_%s_pool' % name))
    return sym.Concat(t3, t7, p, name='ch_concat_%s_chconcat' % name)


def _inception7e(data, n1, n3r, n3, nd3r, nd3, pool, proj, name):
    t1 = _conv(data, n1, name=('%s_conv' % name))
    t3 = _conv(data, n3r, name=('%s_tower' % name), suffix='_conv')
    t3a = _conv(t3, n3, kernel=(1, 3), pad=(0, 1), name=('%s_tower' % name),
                suffix='_mixed_conv')
    t3b = _conv(t3, n3, kernel=(3, 1), pad=(1, 0), name=('%s_tower' % name),
                suffix='_mixed_conv_1')
    td3 = _conv(data, nd3r, name=('%s_tower_1' % name), suffix='_conv')
    td3 = _conv(td3, nd3, kernel=(3, 3), pad=(1, 1),
                name=('%s_tower_1' % name), suffix='_conv_1')
    td3a = _conv(td3, nd3, kernel=(1, 3), pad=(0, 1),
                 name=('%s_tower_1' % name), suffix='_mixed_conv')
    td3b = _conv(td3, nd3, kernel=(3, 1), pad=(1, 0),
                 name=('%s_tower_1' % name), suffix='_mixed_conv_1')
    p = _pool(data, (3, 3), (1, 1), (1, 1), pool,
              ('%s_pool_%s_pool' % (pool, name)))
    cp = _conv(p, proj, name=('%s_tower_2' % name), suffix='_conv')
    return sym.Concat(t1, t3a, t3b, td3a, td3b, cp,
                      name='ch_concat_%s_chconcat' % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable('data')
    # stem
    body = _conv(data, 32, kernel=(3, 3), stride=(2, 2), name='conv')
    body = _conv(body, 32, kernel=(3, 3), name='conv_1')
    body = _conv(body, 64, kernel=(3, 3), pad=(1, 1), name='conv_2')
    body = _pool(body, (3, 3), (2, 2), (0, 0), 'max', 'pool')
    body = _conv(body, 80, kernel=(1, 1), name='conv_3')
    body = _conv(body, 192, kernel=(3, 3), name='conv_4')
    body = _pool(body, (3, 3), (2, 2), (0, 0), 'max', 'pool1')
    # stage 3
    body = _inception7a(body, 64, 48, 64, 64, 96, 32, 'mixed')
    body = _inception7a(body, 64, 48, 64, 64, 96, 64, 'mixed_1')
    body = _inception7a(body, 64, 48, 64, 64, 96, 64, 'mixed_2')
    body = _inception7b(body, 384, 64, 96, 'mixed_3')
    # stage 4
    body = _inception7c(body, 192, 128, 192, 128, 192, 192, 'mixed_4')
    body = _inception7c(body, 192, 160, 192, 160, 192, 192, 'mixed_5')
    body = _inception7c(body, 192, 160, 192, 160, 192, 192, 'mixed_6')
    body = _inception7c(body, 192, 192, 192, 192, 192, 192, 'mixed_7')
    body = _inception7d(body, 192, 320, 192, 192, 'mixed_8')
    # stage 5
    body = _inception7e(body, 320, 384, 384, 448, 384, 'avg', 192, 'mixed_9')
    body = _inception7e(body, 320, 384, 384, 448, 384, 'max', 192, 'mixed_10')
    pool = sym.Pooling(data=body, kernel=(8, 8), stride=(1, 1),
                       global_pool=True, pool_type='avg', name='global_pool')
    flat = sym.Flatten(data=pool, name='flatten')
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name='fc1')
    return sym.SoftmaxOutput(data=fc1, name='softmax')
