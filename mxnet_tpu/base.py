"""Base utilities: errors, dtype registry, attribute parsing.

TPU-native analogue of the reference's `python/mxnet/base.py` +
`include/mxnet/base.h`. There is no C ABI here: the "library" is JAX/XLA, so
this module only carries the shared small pieces (error type, dtype codes,
string-attr coercion used for reference-compatible kwargs).

Reference: python/mxnet/base.py:41-108 (lib loading / MXNetError),
include/mxnet/base.h:86-90 (version).
"""
from __future__ import annotations

import ast
from typing import Any

import numpy as np

__version__ = "0.9.5-tpu.1"

# Integer dtype codes match the reference's mshadow enum so that saved-param
# blobs are interchangeable (reference: python/mxnet/ndarray.py _DTYPE_NP_TO_MX).
_DTYPE_NP_TO_MX = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
}
_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}
# TPU-native extensions (codes outside the reference range).
try:  # bfloat16 is the TPU-native compute dtype
    import ml_dtypes

    _DTYPE_NP_TO_MX[np.dtype(ml_dtypes.bfloat16)] = 16
    _DTYPE_MX_TO_NP[16] = np.dtype(ml_dtypes.bfloat16)
    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    bfloat16 = None
_DTYPE_NP_TO_MX[np.dtype(np.int64)] = 17
_DTYPE_MX_TO_NP[17] = np.dtype(np.int64)
_DTYPE_NP_TO_MX[np.dtype(np.bool_)] = 18
_DTYPE_MX_TO_NP[18] = np.dtype(np.bool_)


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: python/mxnet/base.py:71)."""


def dtype_np_to_mx(dtype) -> int:
    return _DTYPE_NP_TO_MX[np.dtype(dtype)]


def dtype_mx_to_np(code: int) -> np.dtype:
    return _DTYPE_MX_TO_NP[code]


def string_types():
    return (str,)


def coerce_attr(value: Any) -> Any:
    """Coerce a reference-style string attribute ("(2,2)", "true", "0.9")
    into a Python value. The reference parses kwargs through dmlc::Parameter
    string fields (SURVEY §5.6); we accept both native Python values and their
    string forms for drop-in compatibility.
    """
    if not isinstance(value, str):
        return value
    s = value.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return value


def attrs_key(attrs: dict) -> tuple:
    """Hashable, deterministic key for an attrs dict (for jit caches)."""

    def freeze(v):
        if isinstance(v, dict):
            return tuple(sorted((k, freeze(x)) for k, x in v.items()))
        if isinstance(v, (list, tuple)):
            return tuple(freeze(x) for x in v)
        if isinstance(v, np.ndarray):
            return (v.dtype.str, v.shape, v.tobytes())
        return v

    return tuple(sorted((k, freeze(v)) for k, v in attrs.items()))
