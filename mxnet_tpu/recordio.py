"""RecordIO — binary-compatible record file format.

Reimplementation of python/mxnet/recordio.py + dmlc-core recordio
(SURVEY §2.1 #27, #36). The on-disk format matches the reference so .rec
datasets packed by the original im2rec are readable:

record  = [kMagic uint32][lrec uint32][data][pad to 4B]
lrec    = cflag<<29 | length   (cflag: 0=whole, 1=start, 2=middle, 3=end)
IRHeader = struct {uint32 flag; float label; uint64 id; uint64 id2}
           + (flag>1 ? flag*float32 labels : inline label)
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

_KMAGIC = 0xCED7230A
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])


class MXRecordIO:
    """Sequential reader/writer (reference recordio.py MXRecordIO).

    ``uri`` goes through the scheme registry (mxnet_tpu.filesystem — the
    dmlc::Stream s3://hdfs:// seam), so records can live in object storage
    or the in-process ``memory://`` store, not just local files."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def open(self):
        from .filesystem import open_stream

        if self.flag == "w":
            self.fp = open_stream(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fp = open_stream(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def __del__(self):
        self.close()

    def close(self):
        if self.is_open:
            self.fp.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.fp.tell()

    def write(self, buf):
        assert self.writable
        length = len(buf)
        self.fp.write(struct.pack("II", _KMAGIC, length & ((1 << 29) - 1)))
        self.fp.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.fp.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("II", header)
        if magic != _KMAGIC:
            raise IOError("Invalid magic number in record file %s" % self.uri)
        length = lrec & ((1 << 29) - 1)
        buf = self.fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Indexed random-access reader/writer (reference MXIndexedRecordIO).
    .idx file: "<key>\\t<byte offset>\\n" per record."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        from .filesystem import exists, open_stream

        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and exists(self.idx_path):
            with open_stream(self.idx_path, "rb") as fin:
                for line in fin.read().decode().splitlines():
                    line = line.strip().split("\t")
                    if len(line) < 2:
                        continue
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            from .filesystem import open_stream

            with open_stream(self.idx_path, "wb") as fout:
                for key in self.keys:
                    fout.write(("%s\t%d\n" % (str(key),
                                              self.idx[key])).encode())
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fp.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


def pack(header, s):
    """Pack a header + byte payload (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
        payload = struct.pack(_IR_FORMAT, header.flag, header.label, header.id, header.id2) + s
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        payload = (
            struct.pack(_IR_FORMAT, header.flag, header.label, header.id, header.id2)
            + label.tobytes()
            + s
        )
    return payload


def unpack(s):
    """(reference recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4 :]
    return header, s


def unpack_img(s, iscolor=-1):
    """(reference recordio.py unpack_img) — requires cv2 or PIL."""
    header, s = unpack(s)
    img = _imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """(reference recordio.py pack_img)."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def _imdecode(buf, iscolor=-1):
    try:
        import cv2

        return cv2.imdecode(buf, iscolor)
    except ImportError:
        from io import BytesIO

        from PIL import Image

        img = np.asarray(Image.open(BytesIO(buf.tobytes())))
        if img.ndim == 3:
            img = img[:, :, ::-1]  # RGB -> BGR to match cv2 convention
        return img


def _imencode(img, quality=95, img_fmt=".jpg"):
    try:
        import cv2

        if img_fmt.lower() in (".jpg", ".jpeg"):
            params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        else:
            params = [cv2.IMWRITE_PNG_COMPRESSION, 3]
        ret, buf = cv2.imencode(img_fmt, img, params)
        assert ret
        return buf.tobytes()
    except ImportError:
        from io import BytesIO

        from PIL import Image

        arr = img[:, :, ::-1] if img.ndim == 3 else img  # BGR -> RGB
        bio = BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(arr).save(bio, format=fmt, quality=quality)
        return bio.getvalue()
