"""Locate native shared libraries and report the package version.

Capability parity with python/mxnet/libinfo.py (reference :1-47): the
reference's ``find_lib_path`` hunts for ``libmxnet.so``; ours locates the
TPU-native runtime libraries built from ``native/`` (``libmxtpu_engine.so``,
``libmxtpu_io.so``) used by the host-side dependency engine and the C++
data plane. ``MXNET_LIBRARY_PATH``-style override via ``MXTPU_LIBRARY_PATH``.
"""
from __future__ import annotations

import os

from .base import __version__  # single source of truth (base.py)

_LIB_NAMES = ("libmxtpu_engine.so", "libmxtpu_io.so")


def find_lib_path():
    """Return the paths of the native runtime libraries that exist.

    Search order: ``MXTPU_LIBRARY_PATH`` env dir, the in-tree ``native/``
    directories (package-local and repo-root), then system default.
    Raises RuntimeError if none found — mirrors reference libinfo.py:13-40.
    """
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    candidates = []
    env_dir = os.environ.get("MXTPU_LIBRARY_PATH")
    if env_dir:
        candidates.append(env_dir)
    candidates += [
        os.path.join(curr, "native"),
        os.path.join(curr, "..", "native"),
    ]
    found = []
    for d in candidates:
        for name in _LIB_NAMES:
            p = os.path.join(d, name)
            if os.path.exists(p) and os.path.isfile(p):
                found.append(os.path.abspath(p))
    if not found:
        raise RuntimeError(
            "Cannot find native runtime libraries %s in candidates:\n%s\n"
            "Build them with `make -C native`."
            % (list(_LIB_NAMES), "\n".join(candidates)))
    return found
