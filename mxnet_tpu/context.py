"""Device contexts.

TPU-native analogue of the reference `Context {dev_type, dev_id}`
(include/mxnet/base.h:116-207, python/mxnet/context.py). A Context resolves
to a concrete `jax.Device`. `tpu(i)` is the accelerator context; `gpu(i)` is
kept as an alias so reference scripts run unchanged. CPU contexts with
distinct dev_ids are first-class (the reference's multi-device-without-
hardware test trick, SURVEY §4.3) — on a host with
``--xla_force_host_platform_device_count=N`` they map to distinct XLA CPU
devices, emulating a mesh.
"""
from __future__ import annotations

import threading
from typing import List, Optional


class Context:
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX resolution ---------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device (lazily; raises if absent)."""
        import jax

        kind = self.device_type
        if kind == "cpu_pinned":
            kind = "cpu"
        if kind == "gpu":  # reference scripts say gpu; on this stack it means
            # the accelerator backend (TPU). Fall back to whatever the default
            # backend exposes.
            devs = _accelerator_devices()
            if not devs:
                raise ValueError("No accelerator device available for %r" % self)
            return devs[self.device_id]
        if kind == "tpu":
            devs = _accelerator_devices()
            if not devs:
                raise ValueError("No TPU device available for %r" % self)
            return devs[self.device_id]
        try:
            cpus = jax.devices("cpu")
        except RuntimeError:
            # jax_platforms pinned to the accelerator plugin only (no cpu
            # backend registered): host-context arrays live on the device
            return jax.devices()[self.device_id % len(jax.devices())]
        return cpus[self.device_id % len(cpus)]


def _accelerator_devices():
    import jax

    try:
        devs = jax.devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"] or []


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_devices(kind: Optional[str] = None) -> int:
    import jax

    if kind in (None, "tpu", "gpu"):
        n = len(_accelerator_devices())
        if kind is not None or n:
            return n
    return len(jax.devices("cpu"))


def default_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def current_context() -> Context:
    return default_context()


def context_list(ctx) -> List[Context]:
    if ctx is None:
        return [default_context()]
    if isinstance(ctx, Context):
        return [ctx]
    return list(ctx)
